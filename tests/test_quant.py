"""Quantized serving (serve.quant.*): block-scaled int8/fp8 KV cache
(serve/cache.py) + weight-only int8 decode matmuls (ops/quant_mm.py).

The contract under test, layer by layer:

- the quantized decode-attention kernels (scan AND interpreted pallas,
  paged / shared-table / scratch-tail / G-query spec forms) stay within a
  STATED tolerance of the bf16 reference — and match each other tightly;
- the write path's running block scale requantizes without forgetting
  (growing amax keeps earlier positions accurate to the new scale), and a
  copy-on-write block copy carries its scale rows;
- the full engine with quantization + prefix sharing + speculation live
  is EXACTLY reproducible: generate()'s ``serve`` override runs the same
  quantized step, so engine-vs-generate parity is equality, not a bound;
- nonfinite values propagate to exactly the affected slots/channels (a
  poisoned block scale cannot silently read as zeros), and a healthy
  quantized engine trips neither serve_nonfinite nor entropy_floor;
- the measured capacity gain is real: derive_slot_budget's quant pair
  prices the quantized step's own memory plan.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.models.generate import generate
from tony_tpu.ops.decode_attention import (
    decode_attention, reference_decode_attention,
)
from tony_tpu.ops.quant_mm import WEIGHT_QMAX, quant_matmul, quantize_weights
from tony_tpu.serve import Engine, Request, ServeConfig
from tony_tpu.serve.cache import (
    block_bytes, create_cache, dequantize_values, kv_quant_spec,
    quant_scatter_span,
)

# stated quant-vs-bf16 logits tolerance (bench decode.quant reports the
# same number; perf-diff pins it as config identity so it cannot loosen)
TOL = 0.08
WTOL = 0.02  # weight-only matmul relative error bound


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, n).astype(np.int32) for n in lengths]


# --- weight-only int8 matmul --------------------------------------------------


class TestQuantMM:
    def test_matches_bf16_within_tolerance_both_impls(self):
        k1, k2 = jax.random.split(jax.random.key(1))
        x = jax.random.normal(k1, (6, 32), jnp.bfloat16)
        w = jax.random.normal(k2, (32, 48), jnp.bfloat16)
        ref = (x.astype(jnp.float32) @ w.astype(jnp.float32))
        wq, s = quantize_weights(w)
        assert wq.dtype == jnp.int8 and s.shape == (48,)
        denom = float(jnp.abs(ref).max())
        for impl in ("scan", "pallas"):
            y = quant_matmul(x, wq, s, impl=impl, block_n=16)
            assert y.shape == ref.shape and y.dtype == x.dtype
            rel = float(jnp.abs(y.astype(jnp.float32) - ref).max()) / denom
            assert rel < WTOL, (impl, rel)
        ys = quant_matmul(x, wq, s, impl="scan", block_n=16)
        yp = quant_matmul(x, wq, s, impl="pallas", block_n=16)
        np.testing.assert_allclose(
            np.asarray(ys, np.float32), np.asarray(yp, np.float32),
            rtol=0, atol=2e-2,
        )

    def test_roundtrip_error_bounded_per_channel(self):
        w = jax.random.normal(jax.random.key(3), (16, 24), jnp.float32)
        wq, s = quantize_weights(w)
        back = wq.astype(jnp.float32) * s[None, :]
        # symmetric rounding: per-channel error <= half an int8 step
        assert float(jnp.abs(back - w).max()) <= float(s.max()) / 2 + 1e-6
        assert float(jnp.abs(wq).max()) <= WEIGHT_QMAX

    def test_poisoned_scale_channel_propagates_to_that_channel_only(self):
        x = jax.random.normal(jax.random.key(4), (4, 16), jnp.float32)
        wq, s = quantize_weights(
            jax.random.normal(jax.random.key(5), (16, 24), jnp.float32)
        )
        s = s.at[7].set(jnp.nan)
        for impl in ("scan", "pallas"):
            y = np.asarray(quant_matmul(x, wq, s, impl=impl, block_n=8))
            assert not np.isfinite(y[:, 7]).any(), impl
            assert np.isfinite(np.delete(y, 7, axis=1)).all(), impl

    def test_shape_validation(self):
        x = jnp.zeros((2, 8))
        wq, s = quantize_weights(jnp.ones((8, 8)))
        with pytest.raises(ValueError):
            quant_matmul(x, wq, s, impl="nope")
        with pytest.raises(ValueError):
            quant_matmul(x, wq, s[:4])
        with pytest.raises(ValueError):
            quant_matmul(jnp.zeros((2, 4)), wq, s)


# --- quantized paged decode attention -----------------------------------------


def _quantize_pool(pool, qmax=127.0):
    """[P, Hkv, blk, hd] bf16 -> (int8 pool, [P, Hkv] f32 scales)."""
    f = pool.astype(jnp.float32)
    scale = jnp.max(jnp.abs(f), axis=(2, 3)) / qmax
    q = f / jnp.maximum(scale[..., None, None], 1e-30)
    return jnp.clip(jnp.round(q), -qmax, qmax).astype(jnp.int8), scale


def _gathered(pool, tables):
    """Pool blocks -> contiguous [B, Hkv, T, hd] caches for the reference."""
    g = jnp.take(pool, tables, axis=0)         # [B, M, Hkv, blk, hd]
    B, M, Hkv, blk, hd = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(B, Hkv, M * blk, hd)


class TestQuantKernel:
    B, H, Hkv, hd, blk, M = 3, 4, 2, 8, 8, 3

    def _case(self, seed=0, G=1, shared=False, short=False):
        """(q, quant pools + scales, tables, lengths, bf16 pools)."""
        ks = jax.random.split(jax.random.key(seed), 3)
        P = 1 + self.B * self.M
        qshape = (self.B, G, self.H, self.hd) if G > 1 else (self.B, self.H, self.hd)
        q = jax.random.normal(ks[0], qshape, jnp.bfloat16)
        kp = jax.random.normal(ks[1], (P, self.Hkv, self.blk, self.hd), jnp.bfloat16)
        vp = jax.random.normal(ks[2], (P, self.Hkv, self.blk, self.hd), jnp.bfloat16)
        tables = 1 + np.arange(self.B * self.M).reshape(self.B, self.M)
        if shared:  # every row's first block is the same physical block
            tables[:, 0] = 1
        lengths = np.full((self.B,), self.M * self.blk, np.int32)
        if short:  # rows end mid-block; trailing table entries hit scratch
            lengths = np.array(
                [self.blk + 3, 2 * self.blk, self.blk - 1], np.int32
            )
            for b in range(self.B):
                used = -(-int(lengths[b]) // self.blk)
                tables[b, used:] = 0
        tables = jnp.asarray(tables, jnp.int32)
        lengths = jnp.asarray(lengths)
        kq, ks_ = _quantize_pool(kp)
        vq, vs_ = _quantize_pool(vp)
        return q, (kq, vq, ks_, vs_), tables, lengths, (kp, vp)

    @pytest.mark.parametrize("shared,short,G", [
        (False, False, 1),   # plain paged
        (True, False, 1),    # shared tables (prefix-store substrate)
        (False, True, 1),    # mid-block lengths + scratch tails
        (False, False, 3),   # G-query speculative verify form
        (True, True, 3),     # everything at once
    ])
    def test_within_tolerance_of_bf16_and_impls_agree(self, shared, short, G):
        q, (kq, vq, ksc, vsc), tables, lengths, (kp, vp) = self._case(
            seed=10 + G, G=G, shared=shared, short=short,
        )
        ref = reference_decode_attention(
            q, _gathered(kp, tables), _gathered(vp, tables), lengths,
        )
        outs = {}
        for impl in ("scan", "pallas"):
            out = decode_attention(
                q, kq, vq, lengths, tables=tables, impl=impl,
                block=self.blk, k_scale=ksc, v_scale=vsc,
            )
            assert out.shape == ref.shape
            err = float(jnp.abs(
                out.astype(jnp.float32) - ref.astype(jnp.float32)
            ).max())
            assert err < TOL, (impl, shared, short, G, err)
            outs[impl] = np.asarray(out, np.float32)
        np.testing.assert_allclose(
            outs["scan"], outs["pallas"], rtol=0, atol=1e-2,
        )

    def test_poisoned_block_scale_hits_exactly_the_referencing_rows(self):
        q, (kq, vq, ksc, vsc), tables, lengths, _ = self._case(seed=20)
        # poison the scale row of row 0's second block; rows 1/2 never
        # reference it, so their outputs must stay finite
        bad = int(tables[0, 1])
        ksc = ksc.at[bad].set(jnp.nan)
        for impl in ("scan", "pallas"):
            out = np.asarray(decode_attention(
                q, kq, vq, lengths, tables=tables, impl=impl,
                block=self.blk, k_scale=ksc, v_scale=vsc,
            ), np.float32)
            assert not np.isfinite(out[0]).all(), impl
            assert np.isfinite(out[1:]).all(), impl

    def test_scale_args_are_validated(self):
        q, (kq, vq, ksc, vsc), tables, lengths, _ = self._case(seed=30)
        with pytest.raises(ValueError):
            decode_attention(
                q, kq, vq, lengths, tables=tables, k_scale=ksc,
            )  # k without v
        with pytest.raises(ValueError):
            decode_attention(  # quantized needs the paged form
                q, kq.transpose(1, 0, 2, 3), vq.transpose(1, 0, 2, 3),
                lengths, k_scale=ksc, v_scale=vsc,
            )


# --- cache write path: running scales, COW, accounting ------------------------


class TestQuantCache:
    def test_kv_quant_spec(self):
        dt, qmax = kv_quant_spec("int8")
        assert dt == jnp.int8 and qmax == 127.0
        with pytest.raises(ValueError):
            kv_quant_spec("int4")
        if not hasattr(jnp, "float8_e4m3fn"):
            with pytest.raises(ValueError):
                kv_quant_spec("fp8_e4m3")
        else:
            dt8, qmax8 = kv_quant_spec("fp8_e4m3")
            assert qmax8 == 448.0

    def test_running_scale_growth_keeps_old_positions_accurate(self):
        """Write small-amplitude rows, then 8x larger rows into the SAME
        block: the block scale grows, stored rows requantize, and the
        early rows still dequantize to their originals within the (new,
        coarser) scale's half-step."""
        Hkv, blk, hd, P = 2, 8, 4, 3
        pool = jnp.zeros((P, Hkv, blk, hd), jnp.int8)
        scale = jnp.zeros((P, Hkv), jnp.float32)
        rng = np.random.default_rng(0)
        small = jnp.asarray(rng.normal(size=(Hkv, 4, hd)) * 0.25, jnp.float32)
        big = jnp.asarray(rng.normal(size=(Hkv, 4, hd)) * 2.0, jnp.float32)
        pids = jnp.full((4,), 1, jnp.int32)
        ub = jnp.asarray([1, 0], jnp.int32)
        pool, scale = quant_scatter_span(
            pool, scale, small, pids, jnp.arange(4), ub, 127.0,
        )
        sc_small = float(scale[1].max())
        pool, scale = quant_scatter_span(
            pool, scale, big, pids, 4 + jnp.arange(4), ub, 127.0,
        )
        assert float(scale[1].min()) > sc_small  # the running max grew
        deq = dequantize_values(
            pool[1], scale[1][:, None, None], jnp.float32,
        )  # [Hkv, blk, hd]
        got_small = deq[:, :4]
        got_big = deq[:, 4:8]
        step = float(scale[1].max())  # one quant step at the final scale
        assert float(jnp.abs(got_small - small).max()) <= step
        assert float(jnp.abs(got_big - big).max()) <= step
        # untouched block 2 still reads all-zero (scale 0 marker intact)
        assert float(jnp.abs(scale[2]).max()) == 0.0

    def test_cow_copy_carries_scale_rows(self):
        from tony_tpu.serve.engine import _copy_block_fn

        cfg = llama.LlamaConfig.tiny()
        cache = create_cache(cfg, 2, 4, 8, quant_kv="int8")
        assert cache.quantized
        rng = np.random.default_rng(1)
        k = cache.k.at[:, 1].set(
            jnp.asarray(rng.integers(-127, 128, cache.k.shape[2:]), jnp.int8)
        )
        cache = cache._replace(
            k=k,
            k_scale=cache.k_scale.at[:, 1].set(0.37),
            v_scale=cache.v_scale.at[:, 1].set(0.11),
        )
        out = _copy_block_fn(True)(cache, 1, 2)
        np.testing.assert_array_equal(
            np.asarray(out.k[:, 2]), np.asarray(out.k[:, 1])
        )
        assert float(out.k_scale[:, 2].min()) == pytest.approx(0.37)
        assert float(out.v_scale[:, 2].max()) == pytest.approx(0.11)
        # the source block is untouched
        assert float(out.k_scale[:, 1].max()) == pytest.approx(0.37)

    def test_block_bytes_prices_payload_plus_scale_rows(self):
        cfg = llama.LlamaConfig.tiny()
        full = block_bytes(cfg, 8)
        q = block_bytes(cfg, 8, quant_kv="int8")
        itemsize = jnp.dtype(cfg.dtype).itemsize
        scales = 2 * cfg.n_layers * cfg.n_kv_heads * 4
        assert q == full // itemsize + scales  # int8 payload + scale rows
        assert q < 0.6 * full


# --- the engine, end to end ---------------------------------------------------


class TestQuantEngine:
    def test_engine_matches_generate_with_everything_live(self, setup):
        """Quantized KV + int8 weights + prefix sharing + speculation, all
        on: engine-vs-generate parity stays EXACT because generate()'s
        ``serve`` override runs the identical quantized step."""
        cfg, params = setup
        sv = dict(quant_kv="int8", quant_weights=True, prefix=True,
                  spec=True, spec_max_draft=3)
        B, P, m = 3, 10, 6
        rng = np.random.default_rng(5)
        shared = rng.integers(0, cfg.vocab_size, 6)
        prompts = np.stack([
            np.concatenate([shared, rng.integers(0, cfg.vocab_size, P - 6)])
            for _ in range(B)
        ]).astype(np.int32)
        key = jax.random.key(9)
        keys = jax.random.split(key, B)
        from tony_tpu.models.generate import DEFAULT_NUCLEUS_K

        eng = Engine(params, cfg, ServeConfig(
            slots=B, max_len=P + m, prefill_buckets=(P,),
            max_top_k=DEFAULT_NUCLEUS_K, **sv,
        ))
        rids = [
            eng.submit(Request(prompt=prompts[i], max_new_tokens=m,
                               rng=keys[i]))
            for i in range(B)
        ]
        got = eng.run()
        assert eng.cache.quantized
        solo = generate(
            params, jnp.asarray(prompts), cfg, max_new_tokens=m,
            rng=key, serve=sv,
        )
        for i, rid in enumerate(rids):
            assert got[rid].tokens == list(np.asarray(solo[i, P:])), i

    # slow: scan-vs-pallas agreement is already tier-1 at the KERNEL level
    # (TestQuantKernel) — the engine-level token identity re-pays two full
    # engine builds and tier-1 runs close to its wall-clock budget
    @pytest.mark.slow
    def test_scan_and_pallas_quant_engines_emit_identical_tokens(self, setup):
        cfg, params = setup
        prompts = _prompts(cfg, [5, 9], seed=7)
        outs = []
        for impl in ("scan", "pallas"):
            eng = Engine(params, cfg, ServeConfig(
                slots=2, max_len=24, kv_block=8, decode_impl=impl,
                quant_kv="int8", quant_weights=True,
            ))
            rids = [eng.submit(Request(prompt=p, max_new_tokens=4))
                    for p in prompts]
            got = eng.run()
            outs.append([got[r].tokens for r in rids])
        assert outs[0] == outs[1]

    @pytest.mark.slow
    def test_compile_ledger_count_unchanged_by_quantization(self, setup):
        """Quantization changes WHAT compiles, never HOW MANY: the same
        trace pays the same bounded prefill/decode signature families.
        Slow-marked (two full engine builds over a 5-prompt trace) —
        tier-1 runs close to its wall-clock budget."""
        cfg, params = setup
        counts = {}
        for quant in (False, True):
            eng = Engine(params, cfg, ServeConfig(
                slots=2, max_len=40, kv_block=8, prefill_buckets=(8, 16),
                quant_kv="int8" if quant else "",
                quant_weights=quant,
            ))
            for p in _prompts(cfg, [3, 6, 9, 12, 15], seed=8):
                eng.submit(Request(prompt=p, max_new_tokens=3))
            eng.run()
            counts[quant] = (
                eng.metrics.prefill_compiles, eng.metrics.decode_compiles,
            )
        assert counts[True] == counts[False]

    def test_stats_snapshot_reports_quant_gauges(self, setup):
        cfg, params = setup
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=32, kv_block=8, quant_kv="int8",
        ))
        eng.run([Request(prompt=np.arange(1, 6), max_new_tokens=3)])
        snap = eng.stats_snapshot()
        assert snap["kv_bytes_per_token"] == pytest.approx(
            block_bytes(cfg, 8, quant_kv="int8") / 8
        )
        assert snap["quant_pool_resident_bytes"] > 0
        bf = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
        assert "quant_pool_resident_bytes" not in bf.stats_snapshot()
        assert bf.stats_snapshot()["kv_bytes_per_token"] > snap["kv_bytes_per_token"]

    # slow: fp8 availability is a property of the jax line, not of this
    # code — the int8 path above is the tier-1 surface, and the fp8 engine
    # build costs ~3s of a tier-1 budget that runs close to its ceiling.
    # kv_quant_spec's fp8 gate itself stays tier-1 in TestQuantCache.
    @pytest.mark.slow
    def test_fp8_gate(self, setup):
        cfg, params = setup
        if not hasattr(jnp, "float8_e4m3fn"):
            with pytest.raises(ValueError):
                Engine(params, cfg, ServeConfig(
                    slots=1, max_len=16, kv_block=8, quant_kv="fp8_e4m3",
                ))
            return
        eng = Engine(params, cfg, ServeConfig(
            slots=1, max_len=16, kv_block=8, quant_kv="fp8_e4m3",
        ))
        got = eng.run([Request(prompt=np.arange(1, 5), max_new_tokens=3)])
        assert len(got[0].tokens) == 3

    def test_unknown_kv_dtype_refused(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError):
            Engine(params, cfg, ServeConfig(
                slots=1, max_len=16, kv_block=8, quant_kv="int4",
            ))

    def test_measured_quant_slot_budget_beats_bf16(self):
        from tony_tpu.serve.capacity import derive_slot_budget

        cfg = llama.LlamaConfig.tiny()
        out = derive_slot_budget(
            cfg, max_len=64, hbm_bytes=8 * 1024 ** 2, kv_block=8,
            shared_prefix_tokens=32, quant_kv="int8",
        )
        assert out["max_slots_quant"] > out["max_slots_native"]
        assert out["quant_slot_ratio"] > 1.0
        assert out["kv_bytes_per_slot_quant"] < 0.6 * out["kv_bytes_per_slot_native"]
        assert out["max_slots_quant_prefix_shared"] >= out["max_slots_quant"]


# --- health: quantization must not read as sickness ---------------------------


class TestQuantHealth:
    @pytest.fixture(autouse=True)
    def _disarm(self):
        from tony_tpu.obs import health

        health.uninstall()
        yield
        health.uninstall()

    @pytest.mark.slow  # re-pays a full quantized-engine build: healthy
    # decode through the quant path is already proven by the
    # everything-live parity test, and sentinel trip/no-trip mechanics by
    # the health suite (tier-1 runs close to its 870s timeout)
    def test_quantized_engine_trips_no_monitors(self, setup, tmp_path):
        """A healthy model served through the quantized path must not trip
        serve_nonfinite (dequant produces real values) or entropy_floor
        (quantization noise must not collapse the output distribution)."""
        from tony_tpu.obs import health
        from tony_tpu.obs.health import HealthRules, HealthSentinel

        cfg, params = setup
        s = health.install(HealthSentinel(
            HealthRules(), app_dir=str(tmp_path), proc="worker_0_user_a0",
            sample_every=1,
        ))
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=48, kv_block=8, quant_kv="int8",
            quant_weights=True,
        ))
        eng.run([
            Request(prompt=p, max_new_tokens=8)
            for p in _prompts(cfg, [4, 7], seed=11)
        ])
        summary = eng.close()
        assert s.verdict == "healthy"
        assert s.trip_counts() == {}
        assert summary.get("health_verdict", "healthy") == "healthy"

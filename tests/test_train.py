"""Tests for the training loop, data pipeline, and checkpoint glue."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models.llama import LlamaConfig
from tony_tpu.parallel.mesh import MeshShape
from tony_tpu.train import DataConfig, FitConfig, fit


def test_synthetic_data_shapes_and_determinism():
    from tony_tpu.train.data import synthetic_batches

    cfg = DataConfig(global_batch=4, seq_len=16, vocab_size=97, seed=3)
    a = next(synthetic_batches(cfg))
    b = next(synthetic_batches(cfg))
    assert a[0].shape == (4, 16) and a[1].shape == (4, 16)
    np.testing.assert_array_equal(np.asarray(a[0]), np.asarray(b[0]))
    # targets are inputs shifted by one
    src = next(synthetic_batches(cfg))
    np.testing.assert_array_equal(np.asarray(src[0][:, 1:]), np.asarray(src[1][:, :-1]))


def test_synthetic_distribution_is_zipf():
    """The searchsorted inverse-CDF sampler draws the same Zipf marginals
    the old rng.choice(p=) path did: empirical token frequencies over a
    large sample match the analytic probabilities."""
    from tony_tpu.train.data import synthetic_batches

    vocab = 50
    cfg = DataConfig(global_batch=8, seq_len=255, vocab_size=vocab, seed=11,
                     prefetch=0)
    stream = synthetic_batches(cfg)
    counts = np.zeros(vocab, dtype=np.int64)
    total = 0
    for _ in range(20):
        inputs, _ = next(stream)
        flat = np.asarray(inputs).ravel()
        counts += np.bincount(flat, minlength=vocab)
        total += flat.size
    assert counts.min() >= 0 and counts.sum() == total
    ranks = np.arange(1, vocab + 1)
    probs = (1.0 / ranks) / np.sum(1.0 / ranks)
    freq = counts / total
    # ~41k draws: the head of the distribution is tight
    np.testing.assert_allclose(freq[:5], probs[:5], atol=0.012)
    # monotone decay across the tail, coarsely
    assert freq[0] > freq[10] > freq[40]


def test_mmap_data_roundtrip(tmp_path):
    from tony_tpu.train.data import mmap_batches

    tokens = np.arange(4 * (8 + 1) * 3, dtype=np.int32)
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    cfg = DataConfig(global_batch=4, seq_len=8, path=str(path))
    inputs, targets = next(mmap_batches(cfg))
    assert inputs.shape == (4, 8)
    np.testing.assert_array_equal(np.asarray(inputs)[0], tokens[:8])
    np.testing.assert_array_equal(np.asarray(targets)[0], tokens[1:9])


def test_make_batches_native_path_and_resume(tmp_path):
    """make_batches routes token files through the C++ loader when it can
    build, and start_step resumes the shuffled stream exactly (the elastic
    restart contract that mmap_batches pins for the numpy path)."""
    from tony_tpu.train import native_loader
    from tony_tpu.train.data import make_batches

    if not native_loader.available():
        pytest.skip("no g++ / native loader build failed")
    tokens = np.arange(4 * (8 + 1) * 5, dtype=np.int32)
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    cfg = DataConfig(global_batch=4, seq_len=8, path=str(path), seed=7)

    stream = make_batches(cfg)
    first = [next(stream) for _ in range(4)]
    # shapes + shift contract
    assert first[0][0].shape == (4, 8)
    np.testing.assert_array_equal(
        np.asarray(first[0][0][:, 1:]), np.asarray(first[0][1][:, :-1])
    )
    # resume at step 2 replays steps 2..3 exactly
    resumed = make_batches(cfg, start_step=2)
    for expect in first[2:]:
        got = next(resumed)
        np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(expect[0]))
    # native=False pins the deterministic sequential mmap path
    seq_inputs, _ = next(make_batches(DataConfig(
        global_batch=4, seq_len=8, path=str(path), native=False
    )))
    np.testing.assert_array_equal(np.asarray(seq_inputs)[0], tokens[:8])


@pytest.mark.slow  # a full fit just to drive the native loader end-to-end;
# the loader itself is unit-covered above and every other fit test pays the
# same train path (tier-1 runs close to its 870s timeout)
def test_fit_on_token_file_native_loader(tmp_path):
    """fit() trains end-to-end from a real token file through the native
    loader (the reference delegates input IO to user scripts; here it is a
    first-class wired component)."""
    from tony_tpu.train import native_loader

    if not native_loader.available():
        pytest.skip("no g++ / native loader build failed")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=4 * 33 * 8, dtype=np.int32)
    path = tmp_path / "corpus.bin"
    tokens.tofile(path)
    cfg = FitConfig(
        model=LlamaConfig.tiny(),
        data=DataConfig(global_batch=4, seq_len=32, path=str(path)),
        mesh_shape=MeshShape(fsdp=2),
        steps=6,
        log_every=3,
        lr=5e-3,
        warmup_steps=2,
    )
    final = fit(cfg)
    assert np.isfinite(final["final_loss"])


def test_prefetch_stream_order_and_exact_resume(tmp_path):
    """prefetch>0 yields the exact same stream as prefetch=0 (deterministic
    FIFO ordering), and start_step resumes it mid-stream bitwise."""
    from tony_tpu.train.data import make_batches
    from tony_tpu.train.prefetch import PrefetchIterator

    tokens = np.arange(4 * (8 + 1) * 5, dtype=np.int32)
    path = tmp_path / "tokens.bin"
    tokens.tofile(path)
    for kwargs in (
        dict(global_batch=4, seq_len=16, vocab_size=97, seed=3),   # synthetic
        dict(global_batch=4, seq_len=8, path=str(path), native=False),  # mmap
    ):
        sync = make_batches(DataConfig(prefetch=0, **kwargs))
        assert not isinstance(sync, PrefetchIterator)  # legacy path untouched
        want = [next(sync) for _ in range(5)]

        pre = make_batches(DataConfig(prefetch=2, **kwargs))
        assert isinstance(pre, PrefetchIterator)
        got = [next(pre) for _ in range(5)]
        pre.close()
        for (wi, wt), (gi, gt) in zip(want, got):
            np.testing.assert_array_equal(np.asarray(wi), np.asarray(gi))
            np.testing.assert_array_equal(np.asarray(wt), np.asarray(gt))

        resumed = make_batches(DataConfig(prefetch=2, **kwargs), start_step=2)
        for wi, wt in want[2:]:
            gi, gt = next(resumed)
            np.testing.assert_array_equal(np.asarray(wi), np.asarray(gi))
            np.testing.assert_array_equal(np.asarray(wt), np.asarray(gt))
        resumed.close()


def test_prefetch_clean_shutdown_no_leaked_threads():
    import threading

    from tony_tpu.train.data import make_batches

    def prefetch_threads():
        return [
            t for t in threading.enumerate()
            if t.name.startswith("tony-prefetch") and t.is_alive()
        ]

    before = len(prefetch_threads())
    stream = make_batches(DataConfig(global_batch=4, seq_len=16, vocab_size=97,
                                     prefetch=3))
    next(stream)
    assert len(prefetch_threads()) == before + 1
    stream.close()
    assert len(prefetch_threads()) == before
    # close is idempotent and next() after close doesn't hang
    stream.close()


def test_prefetch_propagates_producer_error():
    from tony_tpu.train.prefetch import PrefetchIterator

    def boom():
        yield 1
        raise RuntimeError("producer died")

    it = PrefetchIterator(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="producer died"):
        # the producer may need a moment to post the error
        for _ in range(3):
            next(it)
    it.close()


@pytest.mark.slow  # two full fits compared end-to-end (the PR 14 two-fit
# pattern); stream order/content identity is covered at the loader level and
# tier-1 runs close to its 870s timeout
def test_prefetch_bitwise_identical_loss_trajectory():
    """prefetch=0 vs prefetch=2 must produce the SAME training run: same
    per-step losses (the stream order and content are identical, and the
    overlapped loop changes only when work happens, not what runs)."""
    import dataclasses

    def run(depth):
        seen = []
        cfg = FitConfig(
            model=LlamaConfig.tiny(),
            data=DataConfig(global_batch=4, seq_len=32, vocab_size=256,
                            prefetch=depth),
            mesh_shape=MeshShape(fsdp=2),
            steps=4,
            log_every=1,
            lr=5e-3,
            warmup_steps=2,
            on_metrics=lambda m: seen.append((m["step"], m["loss"], m["grad_norm"])),
        )
        final = fit(cfg)
        return seen, final["final_loss"]

    seen0, final0 = run(0)
    seen2, final2 = run(2)
    assert len(seen0) == 4 and seen0 == seen2
    assert final0 == final2  # exact float equality, not allclose


def test_reporter_queue_overflow_drops_instead_of_blocking():
    """A stalled AM RPC can't block the step loop: push() enqueues, the
    overflow increments the drop counter, and the counter is surfaced as a
    metrics_dropped sample on the next successful push."""
    import threading
    import time as _time

    from tony_tpu.obs.reporter import MetricsReporter

    class SlowClient:
        def __init__(self):
            self.release = threading.Event()
            self.sent = []

        def push_metrics(self, job_name, index, samples):
            assert self.release.wait(timeout=10)
            self.sent.append(samples)

        def close(self):
            self.release.set()

    client = SlowClient()
    rep = MetricsReporter(client=client, maxsize=2)
    assert rep.active
    t0 = _time.perf_counter()
    for i in range(20):
        rep.push({"step": i + 1, "loss": 1.0})
    assert _time.perf_counter() - t0 < 1.0  # never blocked on the stall
    assert rep.dropped >= 10
    client.release.set()  # un-wedge the AM; close() flushes the queue
    rep.close()
    assert len(client.sent) >= 1
    names = {n for batch in client.sent for (n, _, _) in batch}
    assert "metrics_dropped" in names


def test_fit_loss_decreases_tiny_model(tmp_path):
    cfg = FitConfig(
        model=LlamaConfig.tiny(),
        data=DataConfig(global_batch=4, seq_len=32, vocab_size=256),
        mesh_shape=MeshShape(dp=2, fsdp=2, tp=2, sp=1),
        steps=40,
        log_every=20,
        lr=5e-3,
        warmup_steps=2,
    )
    final = fit(cfg)
    assert np.isfinite(final["final_loss"])
    # Zipf synthetic data: loss must drop below the uniform ceiling ln(256)=5.55
    assert final["final_loss"] < 5.2


def test_fit_checkpoint_resume(tmp_path):
    common = dict(
        model=LlamaConfig.tiny(),
        data=DataConfig(global_batch=4, seq_len=32, vocab_size=256),
        mesh_shape=MeshShape(fsdp=2),
        log_every=5,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=5,
    )
    fit(FitConfig(steps=5, **common))
    from tony_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.latest_step() == 5
    mgr.close()
    # resume continues to 10 without error and saves step 10
    fit(FitConfig(steps=10, **common))
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.latest_step() == 10
    mgr.close()


@pytest.mark.parametrize("pp_schedule", [
    # gpipe is slow-marked: its schedule math is unit-covered in
    # test_parallel (pipeline_apply value tests) and 1f1b keeps the
    # full-fit e2e for the pp axis (tier-1 runs close to its 870s timeout)
    pytest.param("gpipe", marks=pytest.mark.slow),
    "1f1b",
])
def test_fit_pipeline_parallel_tiny_model(pp_schedule):
    """PP is a first-class fit() axis under both schedules: GPipe (autodiff
    backward) and 1F1B (interleaved hand-scheduled backward); loss
    decreases either way."""
    import dataclasses

    cfg = FitConfig(
        model=dataclasses.replace(LlamaConfig.tiny(), n_layers=4),
        data=DataConfig(global_batch=8, seq_len=32, vocab_size=256),
        mesh_shape=MeshShape(pp=2, fsdp=2, tp=2),
        pp_microbatches=4,
        pp_schedule=pp_schedule,
        steps=30,
        log_every=15,
        lr=5e-3,
        warmup_steps=2,
    )
    final = fit(cfg)
    assert np.isfinite(final["final_loss"])
    assert final["final_loss"] < 5.2


@pytest.mark.slow  # ~11s combination fit; the 1f1b pipeline fit and the
# flash-attention kernel tests each stay under tier-1 on their own
def test_fit_pipeline_with_flash_attention():
    """pp x flash: the pallas kernel runs region-local inside pipeline
    stages (no nested shard_map — shardy forbids re-binding axes)."""
    import dataclasses

    cfg = FitConfig(
        model=dataclasses.replace(
            LlamaConfig.tiny(), n_layers=4, attention_impl="flash"
        ),
        data=DataConfig(global_batch=8, seq_len=32, vocab_size=256),
        mesh_shape=MeshShape(pp=2, fsdp=2, tp=2),
        pp_microbatches=4,
        steps=6,
        log_every=3,
        lr=5e-3,
        warmup_steps=2,
    )
    final = fit(cfg)
    assert np.isfinite(final["final_loss"])


@pytest.mark.parametrize("impl", ["ring", "ring_flash", "ulysses"])
def test_pipeline_rejects_sequence_parallel_attention(impl):
    """pp x ring/ring_flash/ulysses composes two manual shard_map regions,
    which the partitioner cannot express — must fail loudly at build time
    with the ONE consistent _pp_guard message."""
    import dataclasses

    import jax

    from tony_tpu.parallel.mesh import build_mesh, set_default_mesh
    from tony_tpu.parallel.sharding import DEFAULT_RULES
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, make_train_step, pp_rules,
    )

    cfg = dataclasses.replace(
        LlamaConfig.tiny(), n_layers=4, attention_impl=impl
    )
    mesh = build_mesh(MeshShape(pp=2, sp=2, fsdp=2))
    set_default_mesh(mesh)
    rules = pp_rules(DEFAULT_RULES)
    opt = default_optimizer(warmup_steps=1, decay_steps=10)
    state = make_train_state(jax.random.key(0), cfg, mesh, opt, rules)
    step = make_train_step(cfg, mesh, opt, rules, n_microbatches=4)
    tokens = np.random.default_rng(0).integers(0, 256, (8, 33))
    with pytest.raises(NotImplementedError, match="cannot nest inside pipeline"):
        step(state, jnp.asarray(tokens[:, :-1]), jnp.asarray(tokens[:, 1:]))


@pytest.mark.slow  # ~14s full fit; the ep training contract stays
# tier-1 on the dp×ep mesh in test_moe_overlap.TestTrainerComposition
# (three trainer runs incl. the GSPMD reference) — this keeps the
# fsdp×ep×tp mesh-shape variant in the full suite (round 20 offsets)
def test_fit_moe_expert_parallel_tiny_model():
    """EP is a first-class fit() axis: LlamaConfig.tiny_moe trains with the
    expert dim sharded over mesh_shape.ep."""
    cfg = FitConfig(
        model=LlamaConfig.tiny_moe(),
        data=DataConfig(global_batch=8, seq_len=32, vocab_size=256),
        mesh_shape=MeshShape(fsdp=2, ep=2, tp=2),
        steps=30,
        log_every=15,
        lr=5e-3,
        warmup_steps=2,
    )
    final = fit(cfg)
    assert np.isfinite(final["final_loss"])
    assert final["final_loss"] < 5.2


def test_fit_ring_flash_context_parallel():
    """fit() trains with attention_impl='ring_flash' on an sp mesh: the
    sequence axis is sharded, K/V chunks ride the ppermute ring, and the
    pallas kernel (interpreter mode on CPU) runs per chunk."""
    import dataclasses

    cfg = FitConfig(
        model=dataclasses.replace(LlamaConfig.tiny(), attention_impl="ring_flash"),
        data=DataConfig(global_batch=4, seq_len=64, vocab_size=256),
        mesh_shape=MeshShape(sp=2, fsdp=2),
        steps=6,
        log_every=3,
        lr=5e-3,
        warmup_steps=2,
    )
    final = fit(cfg)
    assert np.isfinite(final["final_loss"])

"""End-to-end tests: real client -> AM -> executors -> user processes.

The keystone suite, modelled on the reference's TestTonyE2E (SURVEY.md
section 4): the substrate is faked at the infrastructure level (local
subprocess containers), so every framework code path — submission, gang
barrier, cluster spec, runtimes, heartbeats, failure policy, elastic
restart — is genuine.
"""

import json
import os
import sys

import pytest

from tony_tpu.am.events import read_history
from tony_tpu.cli.client import TonyClient
from tony_tpu.config.config import TonyConfig

FAST = {
    "task.heartbeat_interval_ms": 200,
    "task.max_missed_heartbeats": 10,
    "application.timeout_s": 90,
}


def submit(tmp_path, overrides, src_dir=""):
    cfg = TonyConfig.load(
        overrides={**FAST, "application.stage_dir": str(tmp_path), **overrides}
    )
    client = TonyClient(cfg, src_dir=src_dir)
    code = client.run(quiet=True)
    return code, client.app_dir


def read_status(app_dir):
    with open(os.path.join(app_dir, "status.json")) as f:
        return json.load(f)


def events_of(app_dir, app_id=None):
    ev_dir = os.path.join(app_dir, "events")
    files = [f for f in os.listdir(ev_dir) if f.endswith(".jsonl")]
    assert len(files) == 1
    return read_history(os.path.join(ev_dir, files[0]))


def test_two_workers_succeed(tmp_path):
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "ok",
            "application.framework": "generic",
            "job.worker.instances": 2,
            "job.worker.command": (
                'python -c "import os, json; '
                "spec = json.loads(os.environ['TONY_CLUSTER_SPEC']); "
                'assert len(spec[\'worker\']) == 2"'
            ),
        },
    )
    assert code == 0
    status = read_status(app_dir)
    assert status["state"] == "SUCCEEDED"
    types = [e["type"] for e in events_of(app_dir)]
    assert types[0] == "APPLICATION_INITED"
    assert types[-1] == "APPLICATION_FINISHED"
    assert types.count("TASK_FINISHED") == 2


def test_failure_propagates_exit_code(tmp_path):
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "fail",
            "application.framework": "generic",
            "job.worker.instances": 2,
            "job.worker.command": (
                "python -c \"import os, sys; "
                "sys.exit(7 if os.environ['TONY_TASK_INDEX'] == '1' else 0)\""
            ),
        },
    )
    assert code == 7
    assert read_status(app_dir)["state"] == "FAILED"


def test_untracked_type_never_fails_job(tmp_path):
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "untracked",
            "application.framework": "generic",
            "job.worker.instances": 1,
            "job.worker.command": 'python -c "pass"',
            "job.tensorboard.instances": 1,
            "job.tensorboard.untracked": True,
            # sleeps forever; AM must finish the job and reap it anyway
            "job.tensorboard.command": 'python -c "import time; time.sleep(600)"',
        },
    )
    assert code == 0
    assert read_status(app_dir)["state"] == "SUCCEEDED"


def test_ps_worker_dependency_tf_runtime(tmp_path):
    """PS+worker shape (milestone config #2): FCFS mode, TF_CONFIG contract."""
    check = (
        'python -c "import os, json; tf = json.loads(os.environ[\'TF_CONFIG\']); '
        "assert set(tf['cluster']) == {'ps', 'worker'}; "
        "assert tf['task']['type'] == os.environ['TONY_JOB_NAME']\""
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "psworker",
            "application.framework": "tensorflow",
            "scheduler.mode": "FCFS",
            "job.ps.instances": 1,
            "job.ps.command": check,
            "job.worker.instances": 2,
            "job.worker.depends_on": "ps",
            "job.worker.depends_timeout_s": 30,
            "job.worker.command": check,
        },
    )
    assert code == 0


def test_worker_restart_failed_only(tmp_path):
    """Elastic path (milestone config #5 shape): fail once, restart, succeed."""
    marker = tmp_path / "attempt.marker"
    script = (
        f'python -c "import os, sys; p = {str(marker)!r}; '
        "first = not os.path.exists(p); "
        "open(p, 'a').write('x'); "
        'sys.exit(1 if first else 0)"'
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "restart",
            "application.framework": "generic",
            "restart.policy": "failed_only",
            "restart.max_worker_restarts": 2,
            "job.worker.instances": 1,
            "job.worker.command": script,
        },
    )
    assert code == 0
    status = read_status(app_dir)
    assert status["state"] == "SUCCEEDED"
    worker = next(t for t in status["tasks"] if t["task"] == "worker:0")
    assert worker["attempts"] == 2


def test_gang_restart_restarts_all_workers(tmp_path):
    """Barrier-restart: one worker's failure restarts the whole gang."""
    marker = tmp_path / "gang.marker"
    # worker 0 fails on the first attempt; worker 1 sleeps long enough to be
    # killed by the gang restart, then both succeed on attempt 1.
    script = (
        f'python -c "import os, sys, time; p = {str(marker)!r}; '
        "idx = os.environ['TONY_TASK_INDEX']; "
        "first = not os.path.exists(p); "
        "(open(p, 'a').write('x'), sys.exit(1)) if (first and idx == '0') "
        "else time.sleep(3 if first else 0)\""
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "gang",
            "application.framework": "generic",
            "restart.policy": "gang",
            "restart.max_worker_restarts": 2,
            "job.worker.instances": 2,
            "job.worker.command": script,
        },
    )
    assert code == 0
    status = read_status(app_dir)
    assert status["state"] == "SUCCEEDED"
    assert all(t["attempts"] == 2 for t in status["tasks"])
    assert any(e["type"] == "GANG_RESTART" for e in events_of(app_dir))


def test_executor_crash_detected_via_container_exit(tmp_path):
    """User script SIGKILLs its executor: the container-completion backup
    path must mark the task failed (no result RPC ever arrives)."""
    script = (
        'python -c "import os, signal, time; '
        'os.kill(os.getppid(), signal.SIGKILL); time.sleep(30)"'
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "crash",
            "application.framework": "generic",
            "job.worker.instances": 1,
            "job.worker.command": script,
        },
    )
    assert code != 0
    assert read_status(app_dir)["state"] == "FAILED"


@pytest.mark.slow
def test_jax_distributed_two_process_reduction(tmp_path):
    """Milestone config #4 skeleton: 2-process jax.distributed DP on the CPU
    backend — cross-process global reduction through the real gang barrier."""
    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(
        "import tony_tpu.runtime.jax_tpu as rt\n"
        "rt.initialize()\n"
        "import jax, jax.numpy as jnp\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "mesh = Mesh(jax.devices(), ('dp',))\n"
        "x = jax.make_array_from_process_local_data(\n"
        "    NamedSharding(mesh, P('dp')),\n"
        "    jnp.ones((len(jax.devices()) // 2,), jnp.float32))\n"
        "total = jax.jit(lambda a: jnp.sum(a), out_shardings=NamedSharding(mesh, P()))(x)\n"
        "assert float(total) == len(jax.devices())\n"
        f"print('rank', jax.process_index(), 'reduction ok')\n"
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "jaxdp",
            "application.framework": "jax",
            "application.timeout_s": 150,
            "job.worker.instances": 2,
            "job.worker.command": f"{sys.executable} train.py",
        },
        src_dir=str(src),
    )
    if code != 0:
        logs_dir = os.path.join(app_dir, "logs")
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}", open(os.path.join(logs_dir, n), errors="replace").read()[-2000:])
    assert code == 0


def test_heartbeat_loss_detected(tmp_path):
    """User script SIGSTOPs its executor: heartbeats stop while the
    container stays alive — the AM's missed-heartbeat accounting must mark
    the task LOST and fail the job (the tony.task.max-missed-heartbeats
    path, SURVEY.md section 3.3 heartbeat variant)."""
    script = (
        'python -c "import os, signal, time; '
        "os.kill(int(os.environ['TONY_EXECUTOR_PID']), signal.SIGSTOP); "
        'time.sleep(600)"'
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "hbloss",
            "application.framework": "generic",
            "task.heartbeat_interval_ms": 100,
            "task.max_missed_heartbeats": 5,
            "job.worker.instances": 1,
            "job.worker.command": script,
        },
    )
    assert code != 0
    status = read_status(app_dir)
    assert status["state"] == "FAILED"
    assert status["tasks"][0]["state"] == "LOST"


def test_cli_stop_kills_job(tmp_path):
    """tony stop: detached submit, stop via RPC, KILLED final state."""
    import time as _time

    from tony_tpu.cli.main import main as cli_main

    env_root = str(tmp_path)
    conf = tmp_path / "job.toml"
    conf.write_text(
        '[application]\nname = "stopme"\nframework = "generic"\n'
        f'stage_dir = "{env_root}"\ntimeout_s = 120\n'
        '[job.worker]\ninstances = 1\n'
        'command = "python -c \\"import time; time.sleep(300)\\""\n'
    )
    rc = cli_main(["submit", "--conf", str(conf), "--detach"])
    assert rc == 0
    apps = [d for d in os.listdir(env_root) if d.startswith("stopme")]
    assert len(apps) == 1
    app_dir = os.path.join(env_root, apps[0])
    # wait for the worker to start, then stop
    deadline = _time.monotonic() + 30
    while _time.monotonic() < deadline:
        if os.path.exists(os.path.join(app_dir, "am.addr")):
            break
        _time.sleep(0.2)
    assert cli_main(["stop", app_dir]) == 0
    deadline = _time.monotonic() + 30
    while _time.monotonic() < deadline:
        if os.path.exists(os.path.join(app_dir, "status.json")):
            break
        _time.sleep(0.3)
    status = read_status(app_dir)
    assert status["state"] == "KILLED"
    assert status["exit_code"] == 143


def test_application_timeout(tmp_path):
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "timeout",
            "application.framework": "generic",
            "application.timeout_s": 3,
            "job.worker.instances": 1,
            "job.worker.command": 'python -c "import time; time.sleep(300)"',
        },
    )
    assert code != 0
    status = read_status(app_dir)
    assert status["state"] == "FAILED"


def test_am_sigkill_retry_job_succeeds(tmp_path):
    """AM fault tolerance (am.retry_count, SURVEY.md section 5 "AM itself
    restartable via application attempts"): SIGKILL the AM mid-job; the client
    relaunches it, attempt 2 reaps the orphaned containers from the journal
    (am.state.json) and relaunches the gang, and the job still succeeds."""
    import signal
    import threading
    import time as _time

    cfg = TonyConfig.load(
        overrides={
            **FAST,
            "application.stage_dir": str(tmp_path),
            "application.name": "amkill",
            "application.framework": "generic",
            "am.retry_count": 1,
            "job.worker.instances": 2,
            "job.worker.command": 'python -c "import time; time.sleep(3)"',
        }
    )
    client = TonyClient(cfg)
    result = {}
    t = threading.Thread(target=lambda: result.update(code=client.run(quiet=True)))
    t.start()
    # wait until attempt 1 has allocated containers (journal exists), then
    # kill the AM process outright
    deadline = _time.monotonic() + 30
    state_path_known = False
    while _time.monotonic() < deadline:
        if client._am_proc is not None and os.path.exists(
            os.path.join(client.app_dir, "am.state.json")
        ):
            state_path_known = True
            break
        _time.sleep(0.05)
    assert state_path_known, "AM never journalled its state"
    am_pid = client._am_proc.pid
    os.kill(am_pid, signal.SIGKILL)
    t.join(timeout=90)
    assert not t.is_alive()
    assert result["code"] == 0
    status = read_status(client.app_dir)
    assert status["state"] == "SUCCEEDED"
    # the successor attempt recovered and bumped the generation
    with open(os.path.join(client.app_dir, "am.state.json")) as f:
        snap = json.load(f)
    assert snap["am_attempt"] == 1
    assert snap["generation"] >= 1


def test_am_retry_exhausted_returns_failure(tmp_path):
    """With am.retry_count=0 a vanished AM fails the submission."""
    import signal
    import threading
    import time as _time

    cfg = TonyConfig.load(
        overrides={
            **FAST,
            "application.stage_dir": str(tmp_path),
            "application.name": "amkill0",
            "application.framework": "generic",
            "job.worker.instances": 1,
            "job.worker.command": 'python -c "import time; time.sleep(10)"',
        }
    )
    client = TonyClient(cfg)
    result = {}
    t = threading.Thread(target=lambda: result.update(code=client.run(quiet=True)))
    t.start()
    deadline = _time.monotonic() + 30
    while _time.monotonic() < deadline:
        if client._am_proc is not None and os.path.exists(
            os.path.join(client.app_dir, "am.addr")
        ):
            break
        _time.sleep(0.05)
    os.kill(client._am_proc.pid, signal.SIGKILL)
    t.join(timeout=60)
    assert not t.is_alive()
    assert result["code"] == 1


@pytest.mark.slow
def test_gang_restart_resumes_from_checkpoint(tmp_path):
    """Milestone config #5, end-to-end and config-driven: a real fit() job
    checkpoints per the JOB config (checkpoint.dir / checkpoint.interval_steps
    -> TONY_CHECKPOINT_* glue), a worker dies mid-training, the gang restarts,
    and generation 1 RESUMES from the last orbax step instead of step 0."""
    src = tmp_path / "src"
    src.mkdir()
    ckpt_dir = tmp_path / "ckpt"
    (src / "train.py").write_text(
        "import logging, os\n"
        "logging.basicConfig(level=logging.INFO)\n"
        "from tony_tpu.train import fit, FitConfig\n"
        "from tony_tpu.train.data import DataConfig\n"
        "from tony_tpu.models.llama import LlamaConfig\n"
        "gen = os.environ.get('TONY_GENERATION', '0')\n"
        "ckpt = os.environ['TONY_CHECKPOINT_DIR']\n"
        "def durable_steps():\n"
        "    if not os.path.isdir(ckpt):\n"
        "        return []\n"
        "    return [d for d in os.listdir(ckpt) if d.isdigit()]\n"
        "def maybe_crash(m):\n"
        "    # die only once a checkpoint is durable, so resume is provable\n"
        "    if gen == '0' and m['step'] >= 6 and durable_steps():\n"
        "        os._exit(1)\n"
        "out = fit(FitConfig(\n"
        "    model=LlamaConfig.tiny(),\n"
        "    data=DataConfig(global_batch=8, seq_len=32, vocab_size=128),\n"
        "    steps=10, log_every=1, on_metrics=maybe_crash))\n"
        "print('TRAINING DONE', out)\n"
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "resume",
            "application.framework": "jax",
            "application.timeout_s": 240,
            "restart.policy": "gang",
            "restart.max_worker_restarts": 2,
            "checkpoint.dir": str(ckpt_dir),
            "checkpoint.interval_steps": 2,
            "job.worker.instances": 1,
            "job.worker.command": f"{sys.executable} train.py",
            "job.worker.env": ["JAX_PLATFORMS=cpu"],
        },
        src_dir=str(src),
    )
    logs_dir = os.path.join(app_dir, "logs")
    if code != 0:
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}", open(os.path.join(logs_dir, n), errors="replace").read()[-3000:])
    assert code == 0
    # generation-1 worker resumed from a checkpoint, not step 0
    attempt1 = [n for n in os.listdir(logs_dir) if "attempt1" in n]
    assert attempt1, os.listdir(logs_dir)
    log_text = open(os.path.join(logs_dir, attempt1[0]), errors="replace").read()
    assert "resumed from checkpoint step" in log_text
    assert "TRAINING DONE" in log_text
    # the final checkpoint landed at the last step
    import re as _re

    resumed = int(_re.search(r"resumed from checkpoint step (\d+)", log_text).group(1))
    assert resumed >= 2
    assert any(d.isdigit() and int(d) == 10 for d in os.listdir(ckpt_dir))


def test_notebook_submitter_proxied_roundtrip(tmp_path):
    """NotebookSubmitter + tony-proxy composition: a single-container notebook
    job announces its URL through the AM, the client proxies to it, and an
    HTTP GET through the local proxy port reaches the container's server."""
    import urllib.request

    from tony_tpu.cli.notebook import launch_notebook, notebook_config
    from tony_tpu.rpc import ApplicationRpcClient

    base = TonyConfig.load(
        overrides={**FAST, "application.stage_dir": str(tmp_path),
                   "application.name": "nb"}
    )
    config = notebook_config(base, memory_mb=256)
    assert config.task_specs().keys() == {"notebook"}
    client, proxy, url = launch_notebook(config, timeout_s=60)
    try:
        import time as _time

        # the URL is announced before jupyter finishes booting; retry the GET
        body, status = "", 0
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{proxy.port}/", timeout=10
                ) as r:
                    body, status = r.read().decode(), r.status
                break
            except OSError:
                _time.sleep(0.5)
        assert status == 200
        # jupyter when installed (this image ships it), else the fallback page
        assert "tony-tpu notebook" in body or "jupyter" in body.lower()
    finally:
        addr = open(os.path.join(client.app_dir, "am.addr")).read().strip()
        with ApplicationRpcClient(addr) as c:
            c.stop_application("test done")
        code = client.monitor(quiet=True)
        proxy.stop()
    assert code == 143  # KILLED
    assert read_status(client.app_dir)["state"] == "KILLED"


def test_horovod_job_rendezvous_roundtrip(tmp_path):
    """Milestone config #3 substrate: a framework=horovod job gets a live
    gloo rendezvous store on the AM — every worker PUTs its own rank key and
    polls GET for all peers' keys through the HOROVOD_GLOO_RENDEZVOUS_* env,
    exactly the traffic pattern of gloo's HTTP store bootstrap."""
    script = (
        "python -c \""
        "import os, time, urllib.request, urllib.error;\n"
        "base = 'http://%s:%s' % (os.environ['HOROVOD_GLOO_RENDEZVOUS_ADDR'],"
        " os.environ['HOROVOD_GLOO_RENDEZVOUS_PORT']);\n"
        "rank = os.environ['HOROVOD_RANK']; size = int(os.environ['HOROVOD_SIZE']);\n"
        "assert size == 2, size;\n"
        "req = urllib.request.Request(base + '/hvd/rank' + rank,"
        " data=rank.encode(), method='PUT');\n"
        "urllib.request.urlopen(req, timeout=10);\n"
        "deadline = time.time() + 30\n"
        "for peer in range(size):\n"
        "    while True:\n"
        "        try:\n"
        "            r = urllib.request.urlopen(base + '/hvd/rank%d' % peer, timeout=10)\n"
        "            assert r.read() == str(peer).encode(); break\n"
        "        except urllib.error.HTTPError as e:\n"
        "            assert e.code == 404 and time.time() < deadline\n"
        "            time.sleep(0.2)\n"
        "\""
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "hvd",
            "application.framework": "horovod",
            "job.worker.instances": 2,
            "job.worker.command": script,
        },
    )
    if code != 0:
        logs_dir = os.path.join(app_dir, "logs")
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}", open(os.path.join(logs_dir, n), errors="replace").read()[-1500:])
    assert code == 0


def test_concurrent_jobs_share_rm_store_queue_then_run(tmp_path):
    """The YARN-RM parity E2E (SURVEY.md section 1 L0): two jobs submitted
    concurrently against ONE chip inventory via cluster.rm_root. The second
    job's gang queues in the shared store while the first holds every chip,
    then runs to success after the first finishes — instead of both
    double-booking the chips (which is what two per-process inventories
    would silently do)."""
    import threading
    import time as _time

    rm_root = str(tmp_path / "rm")
    results = {}
    t0 = _time.monotonic()

    def run_job(name, sleep_s):
        code, app_dir = submit(
            tmp_path,
            {
                "application.name": name,
                "application.framework": "generic",
                "cluster.rm_root": rm_root,
                "am.allocation_timeout_s": 60,
                "job.worker.instances": 1,
                # the FULL default local inventory (64 chips): jobs serialize
                "job.worker.tpu_chips": 64,
                "job.worker.command": (
                    f'python -c "import time; time.sleep({sleep_s})"'
                ),
            },
        )
        results[name] = (code, app_dir, _time.monotonic() - t0)

    ta = threading.Thread(target=run_job, args=("rm-first", 3))
    ta.start()
    _time.sleep(1.0)  # let job A take the chips first
    tb = threading.Thread(target=run_job, args=("rm-second", 0))
    tb.start()
    ta.join(90)
    tb.join(90)
    code_a, dir_a, _ = results["rm-first"]
    code_b, dir_b, dur_b = results["rm-second"]
    assert code_a == 0 and read_status(dir_a)["state"] == "SUCCEEDED"
    assert code_b == 0 and read_status(dir_b)["state"] == "SUCCEEDED"
    # job B could not have run concurrently: it waited out A's ~3s sleep
    assert dur_b > 3.0
    # all leases returned at job end
    from tony_tpu.cluster.lease import LeaseStore

    summary = LeaseStore(rm_root).summary()
    assert not summary["apps"] and not summary["queue"]


def test_concurrent_job_clean_rejection_names_holder(tmp_path):
    """With a short allocation timeout the queued job is REJECTED with a
    message naming the holder, and the client exits nonzero."""
    import threading
    import time as _time

    rm_root = str(tmp_path / "rm")
    results = {}

    def run_job(name, sleep_s, timeout_s):
        code, app_dir = submit(
            tmp_path,
            {
                "application.name": name,
                "application.framework": "generic",
                "cluster.rm_root": rm_root,
                "am.allocation_timeout_s": timeout_s,
                "job.worker.instances": 1,
                "job.worker.tpu_chips": 64,
                "job.worker.command": (
                    f'python -c "import time; time.sleep({sleep_s})"'
                ),
            },
        )
        results[name] = (code, app_dir)

    ta = threading.Thread(target=run_job, args=("rm-holder", 8, 60))
    ta.start()
    _time.sleep(1.0)
    tb = threading.Thread(target=run_job, args=("rm-rejected", 0, 2))
    tb.start()
    tb.join(60)
    code_b, dir_b = results["rm-rejected"]
    assert code_b != 0
    status = read_status(dir_b)
    assert status["state"] == "FAILED"
    ta.join(90)
    assert results["rm-holder"][0] == 0


def test_submit_latency_breakdown_recorded(tmp_path):
    """The second north-star metric (BASELINE.json "metric"): submit ->
    first-step latency is measurable from any fit() job's artifacts —
    submitted_at written by the client, the first step-carrying METRICS
    event timestamped by the AM (bypassing the history throttle), and
    submit_latency() assembling the phase breakdown."""
    from tony_tpu.am.events import submit_latency

    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(
        "from tony_tpu.train import fit, FitConfig\n"
        "from tony_tpu.train.data import DataConfig\n"
        "from tony_tpu.models.llama import LlamaConfig\n"
        "fit(FitConfig(model=LlamaConfig.tiny(),\n"
        "    data=DataConfig(global_batch=8, seq_len=32, vocab_size=128),\n"
        "    steps=3, log_every=10))\n"  # log_every > steps: step-1 push must still happen
    )
    code, app_dir = submit(
        tmp_path,
        {
            "application.name": "latency",
            "application.framework": "jax",
            "job.worker.instances": 1,
            "job.worker.command": f"{sys.executable} train.py",
            "job.worker.env": ["JAX_PLATFORMS=cpu"],
        },
        src_dir=str(src),
    )
    assert code == 0
    lat = submit_latency(app_dir)
    # phases are present, ordered, and positive
    assert 0 < lat["am_inited_s"] <= lat["task_started_s"] <= lat["registered_s"]
    assert lat["registered_s"] < lat["first_step_s"] < 120

"""Serving-engine tests: continuous batching must not change what any
single request generates, and the length-aware decode path must match the
full-mask reference exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.models.generate import generate
from tony_tpu.ops.decode_attention import decode_attention, reference_decode_attention
from tony_tpu.serve import Engine, Request, ServeConfig
from tony_tpu.serve.cache import blocks_for


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompts(cfg, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, cfg.vocab_size, n).astype(np.int32) for n in lengths]


# --- engine vs generate() parity ---------------------------------------------


def test_engine_matches_generate_greedy(setup):
    """Greedy requests of different lengths through a 2-slot engine (forced
    slot churn + bucketed prefill + cache growth) produce exactly the tokens
    a solo generate() call produces for each prompt."""
    cfg, params = setup
    prompts = _prompts(cfg, [3, 7, 12, 5])
    budgets = [5, 4, 6, 3]
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
    rids = [
        eng.submit(Request(prompt=p, max_new_tokens=m))
        for p, m in zip(prompts, budgets)
    ]
    got = eng.run()
    for rid, p, m in zip(rids, prompts, budgets):
        solo = generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=m)
        assert got[rid].tokens == list(np.asarray(solo[0, len(p):])), rid


@pytest.mark.slow  # re-pays a full engine build for the sampled variant of
# the greedy parity test above; per-request key isolation is covered at the
# sample_tokens/generate level (tier-1 runs close to its 870s timeout)
def test_engine_matches_generate_sampled(setup):
    """Same rng -> same tokens, batched or solo: a request's sample stream
    depends only on its own key, not on what else occupies the engine."""
    cfg, params = setup
    prompts = _prompts(cfg, [4, 9, 6], seed=1)
    kwargs = [
        dict(temperature=0.8, top_k=7),
        dict(temperature=1.2, top_p=0.9),
        dict(temperature=0.6, top_k=5, top_p=0.7),
    ]
    keys = [jax.random.key(40 + i) for i in range(3)]
    # generate() derives row i's stream from split(rng, B); submit the same
    # derived key so engine-vs-generate compares identical streams (B=1)
    row_keys = [jax.random.split(k, 1)[0] for k in keys]
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
    rids = [
        eng.submit(Request(prompt=p, max_new_tokens=5, rng=rk, **kw))
        for p, rk, kw in zip(prompts, row_keys, kwargs)
    ]
    got = eng.run()
    for rid, p, k, rk, kw in zip(rids, prompts, keys, row_keys, kwargs):
        solo = generate(
            params, jnp.asarray(p)[None], cfg, max_new_tokens=5,
            rng=k, **kw,
        )
        direct = Engine(params, cfg, ServeConfig(slots=1, max_len=32))
        dres = direct.run([Request(prompt=p, max_new_tokens=5, rng=rk, **kw)])
        assert got[rid].tokens == list(np.asarray(solo[0, len(p):]))
        assert dres[0].tokens == got[rid].tokens


def test_eos_frees_slot_for_queued_request(setup):
    """A row hitting EOS releases its slot mid-run and the queued request
    takes it over — the continuous-batching contract."""
    cfg, params = setup
    p1, p2 = _prompts(cfg, [4, 6], seed=2)
    # find what the first greedy token of p1 is, then use it as its EOS
    first = int(generate(params, jnp.asarray(p1)[None], cfg, max_new_tokens=1)[0, -1])
    eng = Engine(params, cfg, ServeConfig(slots=1, max_len=32, kv_block=8))
    a = eng.submit(Request(prompt=p1, max_new_tokens=8, eos_id=first))
    b = eng.submit(Request(prompt=p2, max_new_tokens=3))
    out = eng.run()
    assert out[a].finish_reason == "eos"
    assert out[a].tokens == [first]          # stopped immediately, 7 unspent
    assert out[b].finish_reason == "length"
    assert len(out[b].tokens) == 3
    # request b decoded on the slot request a vacated
    assert eng.metrics.requests_finished == 2
    # b's tokens match its solo run (slot reuse leaked nothing)
    solo = generate(params, jnp.asarray(p2)[None], cfg, max_new_tokens=3)
    assert out[b].tokens == list(np.asarray(solo[0, len(p2):]))


def test_bucketed_prefill_compile_count(setup):
    """Ten distinct prompt lengths land in at most len(buckets) prefill
    compiles — admission pads to buckets, so compile count is bounded by
    the bucket set, not by the traffic."""
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(
        slots=2, max_len=40, kv_block=8, prefill_buckets=(8, 16, 24),
    ))
    lengths = [2, 3, 5, 7, 8, 9, 12, 15, 17, 21]
    for p in _prompts(cfg, lengths, seed=3):
        eng.submit(Request(prompt=p, max_new_tokens=2))
    eng.run()
    assert eng.metrics.requests_finished == len(lengths)
    assert eng.metrics.prefill_compiles <= 3
    # decode recompiles only on signature changes — attended table width
    # (doubling ladder) x pool size (doubling ladder), never per request:
    # each axis contributes at most 1 + log2 of its block span
    m_axis = 1 + int(np.ceil(np.log2(blocks_for(40, 8))))
    p_axis = 1 + int(np.ceil(np.log2(eng._pool_cap)))
    assert eng.metrics.decode_compiles <= m_axis + p_axis


def test_cache_grows_and_frees_blocks(setup):
    """Attended width tracks the live maximum: it grows in blocks as the
    longest row extends and shrinks back when that row finishes (a freed
    slot returns the blocks nothing else references)."""
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=64, kv_block=8))
    long = eng.submit(Request(prompt=_prompts(cfg, [20], seed=4)[0],
                              max_new_tokens=8))
    first = eng.run()
    # attended table widths the engine compiled (decode signature =
    # (pool blocks, attended blocks))
    grown = max(att for _, att in eng._decode_fns) * 8
    assert grown >= 24  # 20-token prompt + decode tail crossed 3 blocks
    # drain left no live rows; a new short request shrinks back to one block
    short = eng.submit(Request(prompt=_prompts(cfg, [3], seed=5)[0],
                               max_new_tokens=2))
    second = eng.run()
    assert eng.attended_positions <= 16, eng.attended_positions
    # the finished rows' private blocks went back to the pool: only the
    # prefix store's registered blocks (plus scratch) stay referenced
    assert eng._pool.n_used <= eng._store.n_nodes
    assert first[long].finish_reason == "length"
    assert second[short].finish_reason == "length"
    # run() drains: each call returns (and evicts) only its own completions
    assert long not in second and not eng._completions


# --- decode attention ---------------------------------------------------------


@pytest.mark.parametrize("impl", ["scan", "pallas"])
def test_decode_attention_matches_repeat_reference(impl):
    """Both decode impls (native-GQA scan and the interpreted Pallas
    kernel) match the repeat-expanded full-mask reference at ragged
    lengths, including length-1 rows and a full row."""
    B, H, Hkv, hd, T, block = 4, 8, 2, 16, 64, 16
    ks = jax.random.split(jax.random.key(7), 3)
    q = jax.random.normal(ks[0], (B, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, T, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, T, hd), jnp.float32)
    lengths = jnp.asarray([1, 17, 33, 64], jnp.int32)
    ref = reference_decode_attention(q, k, v, lengths)
    got = decode_attention(q, k, v, lengths, impl=impl, block=block)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), atol=2e-6, rtol=1e-5
    )


def test_decode_attention_ignores_positions_beyond_length():
    """Garbage beyond a row's length (stale cache from a previous slot
    occupant) must not leak into the output — the length mask is the only
    thing standing between slot reuse and cross-request contamination."""
    B, H, Hkv, hd, T = 2, 4, 2, 8, 32
    ks = jax.random.split(jax.random.key(9), 3)
    q = jax.random.normal(ks[0], (B, H, hd), jnp.float32)
    k = jax.random.normal(ks[1], (B, Hkv, T, hd), jnp.float32)
    v = jax.random.normal(ks[2], (B, Hkv, T, hd), jnp.float32)
    lengths = jnp.asarray([5, 9], jnp.int32)
    base = decode_attention(q, k, v, lengths, impl="scan", block=8)
    # poison everything beyond each row's length
    pos = jnp.arange(T)[None, None, :, None]
    poisoned_k = jnp.where(pos < lengths[:, None, None, None], k, 1e3)
    poisoned_v = jnp.where(pos < lengths[:, None, None, None], v, -1e3)
    for impl in ("scan", "pallas"):
        got = decode_attention(
            q, poisoned_k, poisoned_v, lengths, impl=impl, block=8
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(base), atol=1e-6)


def test_engine_decode_impls_agree(setup):
    """The engine produces identical greedy tokens under both decode
    kernels (scan vs interpreted Pallas)."""
    cfg, params = setup
    prompts = _prompts(cfg, [3, 10], seed=6)
    outs = {}
    for impl in ("scan", "pallas"):
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=32, kv_block=8, decode_impl=impl,
        ))
        res = eng.run([Request(prompt=p, max_new_tokens=5) for p in prompts])
        outs[impl] = [res[i].tokens for i in range(len(prompts))]
    assert outs["scan"] == outs["pallas"]


# --- metrics ------------------------------------------------------------------


def test_decode_metrics_populated(setup):
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
    eng.run([
        Request(prompt=p, max_new_tokens=4)
        for p in _prompts(cfg, [3, 5, 4], seed=8)
    ])
    m = eng.metrics.summary()
    assert m["requests_finished"] == 3
    assert m["generated_tokens"] == 12
    assert m["tokens_per_sec_per_chip"] > 0
    assert m["ttft_avg_s"] > 0
    assert 0 < m["slot_occupancy"] <= 1


def test_engine_shutdown_summary(setup, tmp_path, monkeypatch, caplog):
    """Engine.close() surfaces the final DecodeMetrics summary — including
    the compile counts, the classic silent serving regression — plus
    TTFT/TPOT quantiles from the registry histograms, logs it, and
    snapshots the registry into the job history when running under a
    tony-tpu job (TONY_APP_DIR)."""
    import json
    import logging

    cfg, params = setup
    monkeypatch.setenv("TONY_APP_DIR", str(tmp_path))
    monkeypatch.setenv("TONY_JOB_NAME", "serve")
    monkeypatch.setenv("TONY_TASK_INDEX", "0")
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
    eng.run([
        Request(prompt=p, max_new_tokens=4)
        for p in _prompts(cfg, [3, 5], seed=9)
    ])
    with caplog.at_level(logging.INFO, logger="tony_tpu.serve.engine"):
        s = eng.close()
    assert s["requests_finished"] == 2
    assert s["prefill_compiles"] >= 1 and s["decode_compiles"] >= 1
    assert s["ttft_p99_s"] >= s["ttft_p50_s"] > 0
    assert any("engine shutdown" in r.message for r in caplog.records)
    # the registry snapshot landed in the job history for the portal
    # (suffixed: a fit() snapshot from the same process must coexist)
    snap_path = tmp_path / "metrics" / "serve_0_user_engine.json"
    assert snap_path.exists()
    snap = json.loads(snap_path.read_text())
    names = {m["name"] for m in snap["metrics"]}
    assert {"tony_ttft_seconds", "tony_decode_step_seconds",
            "tony_requests_finished_total"} <= names

"""Prefix-store tests: cross-request KV reuse must change WHAT gets
computed (tail-only prefill, shared refcounted blocks, copy-on-write) and
never WHAT gets generated — engine-vs-generate() parity holds with the
store live, draw for draw. Plus the paged-cache edge cases the sharing
machinery leans on: exact block boundaries, shrink with store-pinned
blocks, grow-under-eviction interleave."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import llama
from tony_tpu.models.generate import generate
from tony_tpu.serve import Engine, Request, ServeConfig
from tony_tpu.serve.cache import (
    SCRATCH_BLOCK, BlockPool, block_bytes, blocks_for, create_cache,
    shrink_cache,
)
from tony_tpu.serve.prefix import PrefixStore, fingerprint


@pytest.fixture(scope="module")
def setup():
    cfg = llama.LlamaConfig.tiny()
    params = llama.init_params(jax.random.key(0), cfg)
    return cfg, params


def _prompt(cfg, n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, n).astype(np.int32)


# --- cache / pool units -------------------------------------------------------


def test_blocks_for_exact_boundaries():
    """ceil semantics at the boundaries the block planner leans on: an
    exact multiple must NOT round up an extra block."""
    assert blocks_for(1, 8) == 1
    assert blocks_for(7, 8) == 1
    assert blocks_for(8, 8) == 1       # exact boundary: still one block
    assert blocks_for(9, 8) == 2
    assert blocks_for(16, 8) == 2
    assert blocks_for(17, 8) == 3
    assert blocks_for(0, 8) == 1       # minimum one block


def test_block_pool_refcount_lifecycle():
    pool = BlockPool(4)
    assert pool.n_free == 3            # scratch (id 0) never allocated
    a = pool.alloc()
    assert a != SCRATCH_BLOCK and pool.refcount(a) == 1
    pool.retain(a)
    assert pool.refcount(a) == 2
    assert pool.release(a) is False    # still referenced
    assert pool.release(a) is True     # refcount hit zero: back on free list
    assert pool.n_free == 3
    with pytest.raises(ValueError):
        pool.release(a)                # double free
    with pytest.raises(ValueError):
        pool.retain(a)                 # retain of a free block
    with pytest.raises(ValueError):
        pool.release(SCRATCH_BLOCK)


def test_pool_shrink_bounded_by_pinned_block():
    """A block pinned high (the prefix store's reference) bounds how far
    the pool may shrink; shrinking below a live block refuses."""
    pool = BlockPool(8)
    pids = [pool.alloc() for _ in range(4)]          # LIFO: 7, 6, 5, 4
    high = pids[0]
    for pid in pids[1:]:
        pool.release(pid)
    assert pool.shrink_target() == high + 1
    with pytest.raises(ValueError, match="live block"):
        pool.shrink(high)              # would drop the pinned block
    pool.shrink(high + 1)
    assert pool.n_blocks == high + 1
    pool.release(high)
    assert pool.shrink_target() == 2   # floor: scratch + one


def test_shrink_cache_preserves_kept_blocks(setup):
    """Device-side pool shrink drops only the trailing ids and leaves the
    kept blocks' contents untouched (the refcount contract's device
    half)."""
    cfg, _ = setup
    cache = create_cache(cfg, slots=2, n_blocks=6, block=8)
    marked = cache.k.at[:, 2].set(7.0)
    cache = cache._replace(k=marked)
    small = shrink_cache(cache, 3)
    assert small.n_blocks == 3
    assert bool(jnp.all(small.k[:, 2] == 7.0))
    # shrinking to a size >= current is a no-op
    assert shrink_cache(small, 5).n_blocks == 3


# --- radix store units --------------------------------------------------------


def _store(block=4, budget_blocks=0):
    bb = 100  # synthetic bytes per block
    return PrefixStore(block=block, block_bytes=bb,
                       budget_bytes=budget_blocks * bb)


def test_store_match_full_partial_and_limit():
    store = _store(block=4)
    pool = BlockPool(16)
    toks = list(range(40, 52))                       # 3 full blocks
    pids = [pool.alloc() for _ in range(3)]
    assert store.insert(toks, pids, pool.retain) == 3
    # full match, capped at limit=plen-1: 12-token prompt matches 2 full
    # blocks + 3 tokens INTO the third (the mid-block COW case)
    m = store.match(toks, 11)
    assert m.length == 11 and list(m.full) == pids[:2]
    assert m.partial == pids[2]
    # exact-boundary limit: no partial
    m = store.match(toks[:8], 8)
    assert m.length == 8 and m.partial is None
    # divergent tail: only the shared prefix matches
    other = toks[:6] + [99, 98, 97, 96, 95, 94]
    m = store.match(other, 11)
    assert m.length == 6 and list(m.full) == pids[:1]
    assert m.partial == pids[1]
    # no match at all
    m = store.match([1, 2, 3, 4, 5], 4)
    assert m.length == 0 and not m.full and m.partial is None


def test_store_insert_dedup_and_sibling():
    store = _store(block=4)
    pool = BlockPool(16)
    toks = list(range(8))
    pids = [pool.alloc(), pool.alloc()]
    assert store.insert(toks, pids, pool.retain) == 2
    # re-inserting the same tokens creates nothing and retains nothing
    before = [pool.refcount(p) for p in pids]
    assert store.insert(toks, pids, pool.retain) == 0
    assert [pool.refcount(p) for p in pids] == before
    # a divergent second block becomes a sibling under the shared first
    sib = toks[:4] + [70, 71, 72, 73]
    spid = pool.alloc()
    assert store.insert(sib, [pids[0], spid], pool.retain) == 1
    assert store.n_nodes == 3


def test_store_lru_leaf_eviction():
    store = _store(block=4)
    pool = BlockPool(16)
    a = list(range(0, 8))
    b = list(range(0, 4)) + [50, 51, 52, 53]
    store.insert(a, [pool.alloc(), pool.alloc()], pool.retain)
    store.insert(b, [store.match(a, 4).full[0], pool.alloc()], pool.retain)
    assert store.n_nodes == 3
    store.match(a, 8)                  # touch a's chain: b's leaf is LRU
    freed = store.evict_lru(pool.release)
    assert freed is not None
    # the evicted leaf was b's divergent block, not the shared root block
    assert store.match(a, 8).length == 8
    assert store.match(b, 8).length < 8
    # eviction never removes an internal node before its children
    assert store.n_nodes == 2


def test_store_budget_eviction():
    store = _store(block=4, budget_blocks=2)
    pool = BlockPool(32)
    for i in range(5):
        toks = [100 + i] + list(range(7))
        store.insert(toks, [pool.alloc(), pool.alloc()], pool.retain)
    dropped = store.evict_to_budget(pool.release)
    assert dropped >= 1
    assert store.resident_bytes <= store.budget_bytes
    assert store.n_nodes <= 2


def test_fingerprint_short_prompt_is_none():
    assert fingerprint([1, 2, 3], 4) is None
    assert fingerprint([1, 2, 3, 4], 4) == fingerprint([1, 2, 3, 4, 9], 4)
    assert fingerprint([1, 2, 3, 4], 4) != fingerprint([1, 2, 3, 5], 4)
    assert fingerprint([1, 2, 3, 4], 0) is None


# --- engine: sharing changes the work, never the tokens -----------------------


def test_engine_prefix_parity_with_generate(setup):
    """The acceptance gate: prompts with heavy prefix overlap (duplicates
    included) through a prefix-enabled engine generate exactly what solo
    generate() calls produce — while the store demonstrably served the
    repeats (hit tokens, tail-only prefill)."""
    cfg, params = setup
    base = _prompt(cfg, 20, seed=4)
    prompts = [
        base,
        base.copy(),                                 # exact duplicate
        np.concatenate([base[:16], _prompt(cfg, 4, seed=5)]),  # shared head
        _prompt(cfg, 9, seed=6),                     # unrelated
    ]
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=40, kv_block=8))
    rids = [eng.submit(Request(prompt=p, max_new_tokens=5)) for p in prompts]
    got = eng.run()
    for rid, p in zip(rids, prompts):
        solo = generate(params, jnp.asarray(p)[None], cfg, max_new_tokens=5)
        assert got[rid].tokens == list(np.asarray(solo[0, len(p):])), rid
    assert eng._store.hit_tokens >= 16   # the duplicate + shared head hit
    assert eng.metrics.summary()["prefix_hit_rate"] > 0


def test_cow_fires_on_block_boundary_prompt(setup):
    """A prompt that is an exact block multiple matches all its blocks but
    one token (the plen-1 cap): the final block is shared mid-block, so
    admission must hand the slot a private copy before the tail writes —
    and tokens stay draw-for-draw identical."""
    cfg, params = setup
    p16 = _prompt(cfg, 16, seed=7)
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=40, kv_block=8))
    a = eng.submit(Request(prompt=p16, max_new_tokens=4))
    first = eng.run()
    b = eng.submit(Request(prompt=p16, max_new_tokens=4))
    second = eng.run()
    assert eng._cow_copies == 1
    assert first[a].tokens == second[b].tokens
    solo = generate(params, jnp.asarray(p16)[None], cfg, max_new_tokens=4)
    assert second[b].tokens == list(np.asarray(solo[0, 16:]))


def test_engine_prefix_off_matches_on(setup):
    """Same trace through prefix-on and prefix-off engines: identical
    tokens (sharing is a pure optimisation), different work (the on-engine
    hit the store, the off-engine has none)."""
    cfg, params = setup
    shared = _prompt(cfg, 24, seed=8)
    def trace():
        return [
            Request(prompt=np.concatenate([shared, _prompt(cfg, 3, seed=s)]),
                    max_new_tokens=4, rng=s)
            for s in range(4)
        ]
    outs = {}
    for on in (True, False):
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=40, kv_block=8, prefix=on,
        ))
        res = eng.run(trace())
        outs[on] = [res[i].tokens for i in sorted(res)]
        if on:
            assert eng._store.hit_tokens > 0
        else:
            assert eng._store is None
    assert outs[True] == outs[False]


def test_sampled_requests_parity_under_sharing(setup):
    """Sampling (temperature/top-k/top-p) with a fixed key is unchanged by
    a store hit: the tail prefill's logits are bitwise the full prefill's,
    so the same rng draws the same tokens."""
    cfg, params = setup
    p = _prompt(cfg, 16, seed=9)
    kw = dict(temperature=0.9, top_k=7, top_p=0.8)
    key = jax.random.key(11)
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=40, kv_block=8))
    eng.run([Request(prompt=p, max_new_tokens=5, rng=key, **kw)])
    hit = eng.run([Request(prompt=p, max_new_tokens=5, rng=key, **kw)])
    assert eng._store.hit_tokens > 0
    fresh = Engine(params, cfg, ServeConfig(slots=1, max_len=40, kv_block=8,
                                            prefix=False))
    ref = fresh.run([Request(prompt=p, max_new_tokens=5, rng=key, **kw)])
    assert hit[1].tokens == ref[0].tokens


def test_grow_under_eviction_interleave(setup):
    """A tiny store budget under a stream of distinct long prompts forces
    pool grow and LRU eviction to interleave; the engine keeps serving
    correctly throughout and the pool stays bounded by its cap."""
    cfg, params = setup
    bb = block_bytes(cfg, 8)
    eng = Engine(params, cfg, ServeConfig(
        slots=2, max_len=40, kv_block=8,
        prefix_budget_mb=2 * bb / 2**20,             # two blocks of budget
    ))
    repeat = _prompt(cfg, 17, seed=20)
    for i in range(6):
        res = eng.run([
            Request(prompt=_prompt(cfg, 17, seed=30 + i), max_new_tokens=2),
            Request(prompt=repeat, max_new_tokens=2),
        ])
        assert all(c.finish_reason == "length" for c in res.values())
    assert eng._store.evicted_blocks > 0
    assert eng._pool.n_blocks <= eng._pool_cap
    assert eng._store.resident_bytes <= eng._store.budget_bytes
    # still correct after all that churn
    solo = generate(params, jnp.asarray(repeat)[None], cfg, max_new_tokens=2)
    final = eng.run([Request(prompt=repeat, max_new_tokens=2)])
    rid = next(iter(final))
    assert final[rid].tokens == list(np.asarray(solo[0, 17:]))


def test_freed_slots_return_only_unshared_blocks(setup):
    """After every request finishes, the only live pool references are the
    store's own (one per radix node): slot references all released, shared
    blocks retained by the tree."""
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=40, kv_block=8))
    eng.run([
        Request(prompt=_prompt(cfg, n, seed=40 + n), max_new_tokens=3)
        for n in (9, 17, 12)
    ])
    assert eng.n_live == 0
    assert eng._pool.n_used == eng._store.n_nodes


def test_prefill_flops_scale_with_tail(setup):
    """The compile-ledger acceptance gate: a matched admission's tail
    prefill costs a fraction of the full-prompt prefill's measured
    cost_analysis FLOPs — prefill work scales with the unshared tail, not
    the prompt length."""
    from tony_tpu.obs.compiles import get_ledger

    cfg, params = setup
    shared = _prompt(cfg, 30, seed=50)
    eng = Engine(params, cfg, ServeConfig(slots=1, max_len=40, kv_block=8))
    for s in range(3):
        eng.run([Request(
            prompt=np.concatenate([shared, _prompt(cfg, 3, seed=60 + s)]),
            max_new_tokens=2,
        )])
    flops = {
        e["fn"]: e.get("flops", 0.0)
        for e in get_ledger().entries("aot") if "prefill" in e["fn"]
    }
    full = [v for k, v in flops.items() if k.startswith("serve.prefill[")]
    tail = [v for k, v in flops.items()
            if k.startswith("serve.prefill_tail[")]
    assert full and tail, flops
    if max(full) <= 0:
        pytest.skip("backend exposes no cost_analysis flops")
    assert max(tail) < 0.5 * max(full), flops


def test_submit_rejects_prompt_over_max_len(setup):
    """The over-long-prompt satellite: submit() must fail deterministically
    with the real reason (max_len) and leave the engine fully serviceable
    — no wedged slot, no consumed rid visible to run()."""
    cfg, params = setup
    eng = Engine(params, cfg, ServeConfig(
        slots=1, max_len=16, kv_block=8, prefill_buckets=(16,),
    ))
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(Request(prompt=_prompt(cfg, 16, seed=70), max_new_tokens=2))
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(Request(prompt=_prompt(cfg, 40, seed=70), max_new_tokens=2))
    # the engine still serves a valid request afterwards
    ok = eng.run([Request(prompt=_prompt(cfg, 5, seed=71), max_new_tokens=2)])
    assert len(ok) == 1
    assert next(iter(ok.values())).finish_reason == "length"


def test_gang_worker_treats_oversized_prompt_as_terminal(setup):
    """Through the decode-host path the same ValueError becomes a terminal
    'invalid' chunk — the frontend finishes the request (reason=rejected)
    instead of burning replays on a deterministic failure."""
    from tony_tpu.rpc import pb
    from tony_tpu.serve.gang import DecodeHostService, GangSettings, \
        build_gang_engine

    settings = GangSettings(model="tiny", slots=1, max_len=16)
    svc = DecodeHostService(
        lambda: build_gang_engine(settings), "decode:0",
    )
    try:
        svc.start()
        req = pb.InferenceRequest(
            rid="r1", prompt=[1] * 40, max_new_tokens=2, eos_id=-1,
            rng_seed=1,
        )
        chunks = list(svc.Generate(req, None))
        assert chunks[-1].done
        assert chunks[-1].finish_reason == "invalid"
        assert "max_len" in chunks[-1].message
    finally:
        svc.shutdown()


def test_engine_decode_impls_agree_under_sharing(setup):
    """Both decode kernels (paged scan and the interpreted paged Pallas
    kernel, table as scalar prefetch) produce identical greedy tokens on a
    trace that exercises shared blocks and COW copies."""
    cfg, params = setup
    base = _prompt(cfg, 16, seed=80)
    def trace():
        return [
            Request(prompt=base, max_new_tokens=4),
            Request(prompt=base.copy(), max_new_tokens=4),
            Request(prompt=np.concatenate([base[:8], _prompt(cfg, 4, seed=81)]),
                    max_new_tokens=4),
        ]
    outs = {}
    for impl in ("scan", "pallas"):
        eng = Engine(params, cfg, ServeConfig(
            slots=2, max_len=32, kv_block=8, decode_impl=impl,
        ))
        res = eng.run(trace())
        assert eng._store.hit_tokens > 0
        outs[impl] = [res[i].tokens for i in sorted(res)]
    assert outs["scan"] == outs["pallas"]


def test_stats_and_registry_surfaces(setup):
    """The metrics spine: stats_snapshot carries the store fields the
    series recorder / `tony top` read, the registry carries the
    tony_serve_prefix_* counters the portal scrapes, and close() reports
    the store's lifetime summary."""
    cfg, params = setup
    p = _prompt(cfg, 16, seed=90)
    eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
    eng.run([Request(prompt=p, max_new_tokens=2)])
    eng.run([Request(prompt=p, max_new_tokens=2)])
    snap = eng.stats_snapshot()
    assert snap["prefix_hit_tokens"] > 0
    assert 0 < snap["prefix_hit_rate"] <= 1
    assert snap["prefix_resident_mb"] > 0
    assert snap["pool_blocks"] >= 2
    assert eng._c_prefix_hit.value > 0
    assert eng._c_prompt_tokens.value >= 32
    s = eng.close()
    assert s["prefix"]["prefix_hit_tokens"] > 0
    assert s["prefix"]["cow_copies"] >= 1


# --- frontend prefix-affinity routing -----------------------------------------


def test_frontend_affinity_pins_and_falls_back():
    """Requests sharing a fingerprint pin to one host; exclusion (replay
    after that host died) falls back to another and re-pins there; short
    prompts route purely by load."""
    from tony_tpu.serve.frontend import GangFrontend
    from tony_tpu.serve.gang import GangSettings

    settings = GangSettings(prefix_fingerprint_tokens=4)
    fe = GangFrontend("", settings)
    try:
        fe.add_host("decode:0", "127.0.0.1:1")
        fe.add_host("decode:1", "127.0.0.1:2")
        fp = fingerprint([5, 6, 7, 8, 9], 4)
        first = fe._pick_host(set(), fp)
        for _ in range(4):
            h = fe._pick_host(set(), fp)
            assert h.task_id == first.task_id    # pinned despite load
        assert fe._c_affinity.value >= 4
        # the pinned host is excluded (it died mid-stream): fall back...
        other = fe._pick_host({first.task_id}, fp)
        assert other.task_id != first.task_id
        # ...and the fingerprint re-pinned to the survivor
        assert fe._affinity[fp] == other.task_id
        # a different fingerprint balances by load, not by the pin
        fp2 = fingerprint([9, 9, 9, 9], 4)
        h2 = fe._pick_host(set(), fp2)
        assert h2 is not None
        # no fingerprint (short prompt): least-loaded
        assert fe._pick_host(set(), None) is not None
    finally:
        fe._closed.set()


def test_frontend_submit_fingerprints_only_long_prompts():
    from tony_tpu.serve.frontend import GangFrontend
    from tony_tpu.serve.gang import GangSettings

    settings = GangSettings(prefix_fingerprint_tokens=8)
    fe = GangFrontend("", settings)
    try:
        fe.add_host("decode:0", "127.0.0.1:1")
        rid = fe.submit(list(range(20)), max_new_tokens=1)
        rid2 = fe.submit(list(range(3)), max_new_tokens=1)
        with fe._lock:
            flights = dict(fe._flights)
        # relays may already have finished (connection refused -> error),
        # so read the fingerprints off whatever state still exists
        if rid in flights:
            assert flights[rid].fp is not None
        if rid2 in flights:
            assert flights[rid2].fp is None
    finally:
        fe._closed.set()

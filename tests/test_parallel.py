"""Correctness tests for the parallelism library on the 8-device CPU mesh.

Every scheme is validated against a dense single-device reference — the
harness SURVEY.md section 7 prescribes for kernel-level work ("correctness
harness = compare vs full-attention reference on small shapes").
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tony_tpu.parallel import (
    DEFAULT_RULES,
    MeshShape,
    MoEConfig,
    build_mesh,
    init_moe_params,
    make_ring_attention,
    make_ulysses_attention,
    microbatch,
    moe_block,
    pipeline_apply,
    tree_shardings,
    unmicrobatch,
)
from tony_tpu.parallel.moe import logical_axes as moe_logical_axes


def _xfail_known_jax04_failure(
    exc: BaseException, signatures: tuple[str, ...], what: str
):
    """Pin a pre-existing environment failure to its exact signature (the
    test_examples.py gloo-offline pattern): on this jax line (<0.5) the
    shard_map compat shim drops ``axis_names`` and falls back to the FULL
    manual region, where the expert=tp override / pp x MoE out_specs
    combinations are known-broken on the CPU mesh. xfail ONLY when the
    raised chain carries every known signature under jax<0.5; any other
    failure — or the same test failing on a newer jax — is real and
    re-raises."""
    version = tuple(int(p) for p in jax.__version__.split(".")[:2])
    chain, node = [], exc
    while node is not None:
        chain.append(f"{type(node).__name__}: {node}")
        node = node.__cause__ or node.__context__
    text = "\n".join(chain)
    if version < (0, 5) and all(sig in text for sig in signatures):
        pytest.xfail(f"{what} (known jax {jax.__version__} CPU-mesh failure)")
    raise exc


def ref_causal_attention(q, k, v):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    S = q.shape[1]
    s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


@pytest.fixture(scope="module")
def qkv():
    B, S, H, D = 2, 64, 8, 16
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (B, S, H, D)) for k in ks)


@pytest.mark.parametrize(
    "shape",
    [MeshShape(sp=8), MeshShape(dp=2, sp=4), MeshShape(tp=2, sp=4)],
    ids=["sp8", "dp2sp4", "tp2sp4"],
)
def test_ring_attention_matches_dense(qkv, shape):
    q, k, v = qkv
    expect = ref_causal_attention(q, k, v)
    got = make_ring_attention(build_mesh(shape))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-4)


@pytest.mark.parametrize(
    "shape",
    [MeshShape(sp=8), MeshShape(dp=2, sp=4), MeshShape(tp=2, sp=4)],
    ids=["sp8", "dp2sp4", "tp2sp4"],
)
def test_ulysses_attention_matches_dense(qkv, shape):
    q, k, v = qkv
    expect = ref_causal_attention(q, k, v)
    got = make_ulysses_attention(build_mesh(shape))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-4)


def test_ring_attention_grads_match_dense(qkv):
    q, k, v = qkv
    mesh = build_mesh(MeshShape(sp=8))
    ring = make_ring_attention(mesh)

    g_ring = jax.grad(lambda a: jnp.sum(ring(a, k, v) ** 2))(q)
    g_ref = jax.grad(lambda a: jnp.sum(ref_causal_attention(a, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref), atol=1e-3)


def test_model_level_ring_attention_via_default_mesh():
    """LlamaConfig(attention_impl='ring') end to end on an sp mesh."""
    from tony_tpu.models.llama import LlamaConfig, forward, init_params

    from tony_tpu.parallel.mesh import set_default_mesh

    set_default_mesh(build_mesh(MeshShape(sp=8)))
    cfg_ring = LlamaConfig.tiny(attention_impl="ring")
    cfg_dot = LlamaConfig.tiny(attention_impl="dot")
    params = init_params(jax.random.key(0), cfg_dot)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_dot.vocab_size)
    expect = forward(params, tokens, cfg_dot)
    got = forward(params, tokens, cfg_ring)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-4)


def test_model_level_ulysses_attention_via_default_mesh():
    """LlamaConfig(attention_impl='ulysses') end to end on an sp mesh."""
    from tony_tpu.models.llama import LlamaConfig, forward, init_params

    from tony_tpu.parallel.mesh import set_default_mesh

    # sp=4 == tiny()'s n_heads: ulysses requires n_heads % sp == 0
    set_default_mesh(build_mesh(MeshShape(sp=4)))
    cfg_uly = LlamaConfig.tiny(attention_impl="ulysses")
    cfg_dot = LlamaConfig.tiny(attention_impl="dot")
    params = init_params(jax.random.key(0), cfg_dot)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_dot.vocab_size)
    expect = forward(params, tokens, cfg_dot)
    got = forward(params, tokens, cfg_uly)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-4)


class TestPipeline:
    def _mesh(self, n):
        return Mesh(np.array(jax.devices()[:n]).reshape(n), ("pp",))

    def test_forward_matches_sequential(self):
        n_stages, M, mb, D = 4, 8, 2, 16
        mesh = self._mesh(n_stages)
        Ws = jnp.stack(
            [jax.random.normal(k, (D, D)) * 0.3
             for k in jax.random.split(jax.random.key(0), n_stages)]
        )
        x = jax.random.normal(jax.random.key(9), (M * mb, D))

        def stage_fn(W, h):
            return jnp.tanh(h @ W)

        got = unmicrobatch(pipeline_apply(stage_fn, Ws, microbatch(x, M), mesh=mesh))
        expect = x
        for i in range(n_stages):
            expect = jnp.tanh(expect @ Ws[i])
        np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-5)

    def test_backward_matches_sequential(self):
        n_stages, M, mb, D = 4, 4, 2, 8
        mesh = self._mesh(n_stages)
        Ws = jnp.stack(
            [jax.random.normal(k, (D, D)) * 0.3
             for k in jax.random.split(jax.random.key(1), n_stages)]
        )
        x = jax.random.normal(jax.random.key(2), (M * mb, D))
        xm = microbatch(x, M)

        def stage_fn(W, h):
            return jnp.tanh(h @ W)

        def pp_loss(Ws):
            return jnp.sum(unmicrobatch(pipeline_apply(stage_fn, Ws, xm, mesh=mesh)) ** 2)

        def seq_loss(Ws):
            h = x
            for i in range(n_stages):
                h = jnp.tanh(h @ Ws[i])
            return jnp.sum(h**2)

        np.testing.assert_allclose(
            np.asarray(jax.grad(pp_loss)(Ws)),
            np.asarray(jax.grad(seq_loss)(Ws)),
            atol=1e-4,
        )

    def test_batch_not_divisible_raises(self):
        with pytest.raises(ValueError):
            microbatch(jnp.zeros((5, 2)), 2)


class TestMoE:
    def test_matches_dense_reference_with_ample_capacity(self):
        cfg = MoEConfig(dim=32, ffn_dim=64, n_experts=4, top_k=2, capacity_factor=8.0)
        params = init_moe_params(jax.random.key(0), cfg, dtype=jnp.float32)
        x = jax.random.normal(jax.random.key(1), (2, 16, 32))
        y, aux = moe_block(params, x, cfg)
        assert jnp.isfinite(aux)

        flat = x.reshape(-1, 32)
        probs = jax.nn.softmax(flat @ params["router"], -1)
        top2 = jnp.argsort(probs, axis=-1)[:, -2:]
        outs = []
        for t in range(flat.shape[0]):
            g = probs[t, top2[t]]
            g = g / g.sum()
            o = 0.0
            for i in range(2):
                e = int(top2[t, i])
                h = jax.nn.silu(flat[t] @ params["w1"][e]) * (flat[t] @ params["w3"][e])
                o = o + g[i] * (h @ params["w2"][e])
            outs.append(o)
        ref = jnp.stack(outs).reshape(x.shape)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-4)

    def test_capacity_overflow_drops_not_crashes(self):
        cfg = MoEConfig(dim=32, ffn_dim=64, n_experts=4, top_k=2, capacity_factor=0.25)
        params = init_moe_params(jax.random.key(0), cfg, dtype=jnp.float32)
        x = jax.random.normal(jax.random.key(1), (2, 16, 32))
        y, aux = moe_block(params, x, cfg)
        assert jnp.isfinite(y).all() and jnp.isfinite(aux)

    def test_expert_parallel_sharded_matches_unsharded(self):
        cfg = MoEConfig(dim=32, ffn_dim=64, n_experts=4, top_k=2, capacity_factor=8.0)
        params = init_moe_params(jax.random.key(0), cfg, dtype=jnp.float32)
        x = jax.random.normal(jax.random.key(1), (2, 16, 32))
        expect, _ = moe_block(params, x, cfg)

        mesh = build_mesh(MeshShape(fsdp=2, tp=4))
        rules = dict(DEFAULT_RULES)
        rules["expert"] = "tp"
        shardings = tree_shardings(moe_logical_axes(), mesh, rules)
        params_s = jax.device_put(params, shardings)
        x_s = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"), None, None)))
        got, _ = jax.jit(lambda p, a: moe_block(p, a, cfg))(params_s, x_s)
        try:
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(expect), atol=1e-4
            )
        except AssertionError as e:
            _xfail_known_jax04_failure(
                e,
                ("Not equal to tolerance",
                 "Mismatched elements: 1024 / 1024 (100%)"),
                "expert=tp resharded moe_block diverges everywhere",
            )


def test_multislice_mesh_shape_and_training():
    """Hybrid ICI x DCN mesh (CPU fallback layout): dp crosses 'slices'."""
    from tony_tpu.parallel import build_multislice_mesh

    mesh = build_multislice_mesh(MeshShape(fsdp=2, tp=2), n_slices=2)
    assert dict(mesh.shape) == {"dp": 2, "pp": 1, "fsdp": 2, "ep": 1, "tp": 2, "sp": 1}

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train.trainer import default_optimizer, make_train_state, make_train_step

    cfg = LlamaConfig.tiny()
    opt = default_optimizer(warmup_steps=1, decay_steps=10)
    state = make_train_state(jax.random.key(0), cfg, mesh, opt)
    step = make_train_step(cfg, mesh, opt)
    tokens = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    state, metrics = step(state, tokens[:, :-1], tokens[:, 1:])
    assert jnp.isfinite(float(metrics["loss"]))


def test_pp_train_step_matches_sequential():
    """The GPipe train step computes the SAME loss and gradients as the
    plain sharded trainer on identical params/batch (pipelining is a
    schedule, not an approximation)."""
    import dataclasses

    import jax

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, make_train_step, pp_rules,
    )

    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4)
    opt = default_optimizer(warmup_steps=1, decay_steps=5)
    toks = jax.random.randint(jax.random.key(2), (8, 33), 0, cfg.vocab_size)

    mesh_pp = build_mesh(MeshShape(pp=2, fsdp=2, tp=2))
    state_pp = make_train_state(jax.random.key(0), cfg, mesh_pp, opt, pp_rules())
    step_pp = make_train_step(cfg, mesh_pp, opt, n_microbatches=4)
    _, m_pp = step_pp(state_pp, toks[:, :-1], toks[:, 1:])

    mesh_seq = build_mesh(MeshShape(fsdp=2, tp=2), devices=jax.devices()[:4])
    state_seq = make_train_state(jax.random.key(0), cfg, mesh_seq, opt)
    step_seq = make_train_step(cfg, mesh_seq, opt)
    _, m_seq = step_seq(state_seq, toks[:, :-1], toks[:, 1:])

    assert abs(float(m_pp["loss"]) - float(m_seq["loss"])) < 1e-5
    assert abs(float(m_pp["grad_norm"]) - float(m_seq["grad_norm"])) < 1e-4


def test_llama_moe_ep_sharded_matches_replicated():
    """The MoE llama loss is identical whether the expert dim is sharded
    over ep or fully replicated (the all-to-all is exact)."""
    import jax

    from tony_tpu.models.llama import LlamaConfig, init_params, loss_fn

    cfg = LlamaConfig.tiny_moe()
    params = init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (8, 33), 0, cfg.vocab_size)
    loss_rep = float(loss_fn(params, toks, cfg))

    from tony_tpu.parallel.sharding import DEFAULT_RULES, tree_shardings
    from tony_tpu.models.llama import logical_axes

    mesh = build_mesh(MeshShape(fsdp=2, ep=2, sp=2))
    shardings = tree_shardings(logical_axes(cfg), mesh, DEFAULT_RULES)
    sharded = jax.device_put(params, shardings)
    loss_ep = float(jax.jit(loss_fn, static_argnums=2)(sharded, toks, cfg))
    assert abs(loss_rep - loss_ep) < 1e-4


def test_pp_moe_train_step_matches_sequential():
    """pp x MoE: the pipelined MoE step computes the SAME cross-entropy as
    the sequential trainer (pipelining is a schedule, not an approximation);
    the aux load-balancing term is computed per microbatch — the standard
    semantics for pipelined MoE, since routing statistics exist per
    forwarded chunk — so with a nonzero coef the losses agree only closely.
    """
    import dataclasses

    import jax

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, make_train_step, pp_rules,
    )

    def run(coef):
        cfg = dataclasses.replace(
            LlamaConfig.tiny_moe(), n_layers=4, moe_capacity_factor=8.0,
            moe_aux_coef=coef,
        )
        opt = default_optimizer(warmup_steps=1, decay_steps=5)
        toks = jax.random.randint(jax.random.key(2), (8, 33), 0, cfg.vocab_size)

        mesh_pp = build_mesh(MeshShape(pp=2, ep=2, fsdp=2))
        state_pp = make_train_state(jax.random.key(0), cfg, mesh_pp, opt, pp_rules())
        step_pp = make_train_step(cfg, mesh_pp, opt, n_microbatches=4)
        _, m_pp = step_pp(state_pp, toks[:, :-1], toks[:, 1:])

        mesh_seq = build_mesh(MeshShape(ep=2, fsdp=2), devices=jax.devices()[:4])
        state_seq = make_train_state(jax.random.key(0), cfg, mesh_seq, opt)
        step_seq = make_train_step(cfg, mesh_seq, opt)
        _, m_seq = step_seq(state_seq, toks[:, :-1], toks[:, 1:])
        return m_pp, m_seq

    # coef 0 isolates the CE: must match exactly
    try:
        m_pp, m_seq = run(0.0)
    except Exception as e:
        _xfail_known_jax04_failure(
            e, ("_SpecError",),
            "pp x MoE out_specs rejected under the full-manual fallback",
        )
    assert abs(float(m_pp["loss"]) - float(m_seq["loss"])) < 1e-5
    assert abs(float(m_pp["grad_norm"]) - float(m_seq["grad_norm"])) < 1e-4
    # with the aux term on, per-microbatch routing statistics differ from
    # full-batch ones by O(coef): close, not identical
    m_pp, m_seq = run(0.01)
    assert abs(float(m_pp["loss"]) - float(m_seq["loss"])) < 5e-3


class Test1F1B:
    """The interleaved-backward pipeline schedule (O(P) activation memory)."""

    def _setup(self, P_, M, mb=2, D=8):
        mesh = Mesh(np.array(jax.devices()[:P_]).reshape(P_), ("pp",))
        Ws = jnp.stack(
            [jax.random.normal(k, (D, D)) * 0.3
             for k in jax.random.split(jax.random.key(1), P_)]
        )
        head_w = jax.random.normal(jax.random.key(3), (D, 5)) * 0.3
        x = jax.random.normal(jax.random.key(2), (M * mb, 4, D))
        tgt = jax.random.randint(jax.random.key(4), (M * mb, 4), 0, 5)
        return mesh, Ws, head_w, x, tgt

    @staticmethod
    def _head_fn(hw, y, t):
        logits = y @ hw
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        sel = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        return jnp.mean(lse - sel)

    @pytest.mark.parametrize("P_,M", [(2, 2), (4, 1), (4, 6), (8, 4)])
    def test_loss_and_grads_match_sequential(self, P_, M):
        from tony_tpu.parallel import pipeline_train_1f1b

        mesh, Ws, head_w, x, tgt = self._setup(P_, M)
        head_fn = self._head_fn

        def stage_fn(W_stack, h):  # local stack [1, D, D]: one layer/stage
            return jnp.tanh(h @ W_stack[0])

        def pp_loss(Ws_, hw, x_):
            return pipeline_train_1f1b(
                stage_fn, head_fn, Ws_, hw, microbatch(x_, M),
                microbatch(tgt, M), mesh=mesh,
            )

        def seq_loss(Ws_, hw, x_):
            h = x_
            for i in range(P_):
                h = jnp.tanh(h @ Ws_[i])
            return head_fn(hw, h, tgt)

        lp = jax.jit(pp_loss)(Ws, head_w, x)
        ls = seq_loss(Ws, head_w, x)
        assert abs(float(lp) - float(ls)) < 1e-5
        gp = jax.jit(jax.grad(pp_loss, argnums=(0, 1, 2)))(Ws, head_w, x)
        gs = jax.grad(seq_loss, argnums=(0, 1, 2))(Ws, head_w, x)
        for a, b in zip(gp, gs):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_pp_1f1b_train_step_matches_sequential():
    """pp_schedule='1f1b' computes the same loss/grads as the plain sharded
    trainer — the interleaved backward is a schedule, not an approximation."""
    import dataclasses

    import jax

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, make_train_step, pp_rules,
    )

    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4)
    opt = default_optimizer(warmup_steps=1, decay_steps=5)
    toks = jax.random.randint(jax.random.key(2), (8, 33), 0, cfg.vocab_size)

    mesh_pp = build_mesh(MeshShape(pp=2, fsdp=2, tp=2))
    state_pp = make_train_state(jax.random.key(0), cfg, mesh_pp, opt, pp_rules())
    step_pp = make_train_step(
        cfg, mesh_pp, opt, n_microbatches=4, pp_schedule="1f1b"
    )
    _, m_pp = step_pp(state_pp, toks[:, :-1], toks[:, 1:])

    mesh_seq = build_mesh(MeshShape(fsdp=2, tp=2), devices=jax.devices()[:4])
    state_seq = make_train_state(jax.random.key(0), cfg, mesh_seq, opt)
    step_seq = make_train_step(cfg, mesh_seq, opt)
    _, m_seq = step_seq(state_seq, toks[:, :-1], toks[:, 1:])

    assert abs(float(m_pp["loss"]) - float(m_seq["loss"])) < 1e-5
    assert abs(float(m_pp["grad_norm"]) - float(m_seq["grad_norm"])) < 1e-4


def test_pp_1f1b_rejects_moe_and_sp_attention():
    import dataclasses

    import jax

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train.trainer import pp_1f1b_loss_from_pairs

    mesh = build_mesh(MeshShape(pp=2, fsdp=2, tp=2))
    toks = jnp.zeros((8, 32), jnp.int32)
    with pytest.raises(NotImplementedError, match="MoE"):
        pp_1f1b_loss_from_pairs(
            {}, toks, toks, cfg=LlamaConfig.tiny_moe(), mesh=mesh,
            n_microbatches=4,
        )
    with pytest.raises(NotImplementedError, match="ring"):
        pp_1f1b_loss_from_pairs(
            {}, toks, toks,
            cfg=dataclasses.replace(LlamaConfig.tiny(), attention_impl="ring"),
            mesh=mesh, n_microbatches=4,
        )


def test_pp_1f1b_memory_is_microbatch_independent():
    """The 1F1B claim, measured: compiled temp memory for the GPipe schedule
    grows O(M) (every microbatch's stage inputs live until the autodiff
    backward), while 1F1B's stays O(P) (ring buffer of 2P-1 inputs). At
    M=32, P=4 the measured ratio is ~20x."""
    import dataclasses
    import functools

    import jax

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.train.trainer import (
        default_optimizer, make_train_state, pp_1f1b_loss_from_pairs,
        pp_loss_from_pairs, pp_rules,
    )

    cfg = dataclasses.replace(LlamaConfig.tiny(), n_layers=4, max_seq_len=128)
    mesh = build_mesh(MeshShape(pp=4, fsdp=2))
    opt = default_optimizer(warmup_steps=1, decay_steps=10)
    state = make_train_state(jax.random.key(0), cfg, mesh, opt, pp_rules())
    toks = jax.ShapeDtypeStruct((64, 128), jnp.int32)

    def temp_mb(fn):
        loss = functools.partial(fn, cfg=cfg, mesh=mesh, n_microbatches=32)
        compiled = jax.jit(jax.value_and_grad(loss)).lower(
            state.params, toks, toks
        ).compile()
        return compiled.memory_analysis().temp_size_in_bytes / 2**20

    gpipe, one_f1b = temp_mb(pp_loss_from_pairs), temp_mb(pp_1f1b_loss_from_pairs)
    assert one_f1b < gpipe / 5, (gpipe, one_f1b)


@pytest.mark.parametrize("preset", ["llama2_7b", "llama3_8b"])
def test_real_model_shardings_resolve_on_8dev_mesh(preset):
    """The REAL 7B/8B configs' parameter AND optimizer-state shardings
    resolve on an fsdp4 x tp2 mesh without materialising anything: every
    named dim divides its mesh axes (catches head/ffn/vocab divisibility
    breaks and regressions in the opt-state path-suffix matching)."""
    import jax

    from tony_tpu.models.llama import LlamaConfig, logical_axes
    from tony_tpu.parallel.sharding import tree_shardings
    from tony_tpu.train.trainer import default_optimizer, state_shardings

    import functools

    import numpy as _np

    from tony_tpu.models import llama as _llama

    cfg = getattr(LlamaConfig, preset)()
    mesh = build_mesh(MeshShape(fsdp=4, tp=2))
    opt = default_optimizer()
    shardings = state_shardings(cfg, mesh, opt)

    def check(shapes_tree, shards_tree, what):
        flat_shapes = jax.tree.leaves(shapes_tree)
        flat_shards = jax.tree.leaves(shards_tree)
        assert len(flat_shapes) == len(flat_shards), what
        for leaf, shard in zip(flat_shapes, flat_shards):
            for dim, names in zip(leaf.shape, shard.spec + (None,) * 10):
                if names is None:
                    continue
                axes = names if isinstance(names, tuple) else (names,)
                factor = int(_np.prod([mesh.shape[a] for a in axes]))
                assert dim % factor == 0, (preset, what, leaf.shape, shard.spec)

    params_shape = jax.eval_shape(
        functools.partial(_llama.init_params, cfg=cfg), jax.random.key(0)
    )
    check(params_shape, shardings.params, "params")
    # the optimizer state (Adam mu/nu, matched by path suffix) must divide too
    opt_shape = jax.eval_shape(opt.init, params_shape)
    check(opt_shape, shardings.opt_state, "opt_state")


@pytest.mark.parametrize(
    "shape",
    [MeshShape(sp=4), MeshShape(dp=2, sp=4), MeshShape(tp=2, sp=2)],
    ids=["sp4", "dp2sp4", "tp2sp2"],
)
def test_ring_flash_attention_matches_dense(shape):
    """Ring x flash (pallas inner per chunk): exact vs dense, including
    dk/dv whose accumulators ride the ring back to their owners."""
    from tony_tpu.parallel import make_ring_flash_attention

    B, S, H, D = 2, 256, 4, 32
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D)) for kk in ks)
    attn = make_ring_flash_attention(build_mesh(shape))
    expect = ref_causal_attention(q, k, v)
    got = attn(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-5)

    g_got = jax.grad(
        lambda a, b, c: jnp.sum(attn(a, b, c) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(ref_causal_attention(a, b, c) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), g_got, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, err_msg=name
        )


def test_model_level_ring_flash_attention_via_default_mesh():
    """LlamaConfig(attention_impl='ring_flash') end to end on an sp mesh."""
    from tony_tpu.models.llama import LlamaConfig, forward, init_params

    from tony_tpu.parallel.mesh import set_default_mesh

    set_default_mesh(build_mesh(MeshShape(sp=2)))
    # tiny() has S=64: 2 chunks of 32; blocks clip to the chunk
    cfg_rf = LlamaConfig.tiny(attention_impl="ring_flash")
    cfg_dot = LlamaConfig.tiny(attention_impl="dot")
    params = init_params(jax.random.key(0), cfg_dot)
    tokens = jax.random.randint(jax.random.key(1), (2, 64), 0, cfg_dot.vocab_size)
    expect = forward(params, tokens, cfg_dot)
    got = forward(params, tokens, cfg_rf)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-4)


def test_ring_flash_gqa_native_kv():
    """GQA rides the ring at native kv width (no repeat per ppermute hop):
    fwd + all grads match the expanded-KV dense reference."""
    from tony_tpu.parallel import make_ring_flash_attention

    B, S, H, Hkv, D = 2, 128, 4, 2, 32
    ks = jax.random.split(jax.random.key(3), 3)
    q = jax.random.normal(ks[0], (B, S, H, D))
    k = jax.random.normal(ks[1], (B, S, Hkv, D))
    v = jax.random.normal(ks[2], (B, S, Hkv, D))
    rep = H // Hkv
    attn = make_ring_flash_attention(build_mesh(MeshShape(sp=2)))

    def ref(a, b, c):
        return ref_causal_attention(
            a, jnp.repeat(b, rep, axis=2), jnp.repeat(c, rep, axis=2)
        )

    np.testing.assert_allclose(
        np.asarray(attn(q, k, v)), np.asarray(ref(q, k, v)), atol=1e-5
    )
    g_got = jax.grad(
        lambda a, b, c: jnp.sum(attn(a, b, c) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    g_ref = jax.grad(
        lambda a, b, c: jnp.sum(ref(a, b, c) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    for name, a, b in zip(("dq", "dk", "dv"), g_got, g_ref):
        assert a.shape == b.shape
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, err_msg=name
        )


def test_ring_flash_rejects_indivisible_blocks():
    """A per-device chunk that doesn't divide the flash blocks must raise
    (a cdiv'd partial block would silently read garbage K positions)."""
    import dataclasses

    from tony_tpu.models.llama import LlamaConfig
    from tony_tpu.parallel import make_ring_flash_attention

    B, H, D = 1, 4, 32
    attn = make_ring_flash_attention(build_mesh(MeshShape(sp=2)))
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, 192, H, D)) for kk in ks)
    cfg = dataclasses.replace(
        LlamaConfig.tiny(), flash_block_q=64, flash_block_k=64
    )
    # S_local = 96, blocks 64 -> 96 % 64 != 0: must raise, not corrupt
    with pytest.raises(ValueError, match="multiple of the flash"):
        attn(q, k, v, cfg)


def test_moe_gather_dispatch_matches_einsum_reference():
    """The gather/scatter dispatch (the production path: zero routing
    matmul FLOPs) must match the one-hot einsum reference exactly —
    outputs, aux loss, AND gradients, including dropped-token semantics
    at a tight capacity factor."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from tony_tpu.parallel.moe import MoEConfig, init_moe_params, moe_block

    base = MoEConfig(dim=32, ffn_dim=64, n_experts=4, top_k=2,
                     capacity_factor=0.6)  # tight: forces real drops
    params = init_moe_params(jax.random.key(0), base, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(1), (2, 24, 32), jnp.float32)

    def run(dispatch):
        cfg = dataclasses.replace(base, dispatch=dispatch)

        def loss(p, xx):
            y, aux = moe_block(p, xx, cfg)
            return jnp.sum(y * y) + aux

        val, grads = jax.value_and_grad(loss)(params, x)
        y, aux = moe_block(params, x, cfg)
        return val, grads, y, aux

    v_g, g_g, y_g, aux_g = run("gather")
    v_e, g_e, y_e, aux_e = run("einsum")
    assert abs(float(v_g) - float(v_e)) < 1e-4
    assert abs(float(aux_g) - float(aux_e)) < 1e-6
    import numpy as np

    np.testing.assert_allclose(np.asarray(y_g), np.asarray(y_e), atol=1e-5)
    for k in g_g:
        np.testing.assert_allclose(
            np.asarray(g_g[k]), np.asarray(g_e[k]), atol=1e-4, err_msg=k
        )

"""`tony serve` multi-host inference gangs (docs/SERVE.md "Gang serving").

Layers under test, cheapest first: the engine's bounded-admission seam and
deterministic re-prefill (the foundation of no-request-lost), the
bind-with-retry TOCTOU fix, the lease store's autoscale hooks, the
frontend's routing/admission/replay against in-process hosts, the new
serve chaos invariants — and ONE real client -> AM -> 2-decode-host job
where a chaos kill_container lands mid-stream and every in-flight request
completes on the survivor with a draw-for-draw-identical replay.
"""

import json
import os
import socket
import sys
import threading
import time

import numpy as np
import pytest

from tony_tpu.chaos.invariants import check_invariants
from tony_tpu.config.config import TonyConfig
from tony_tpu.serve.engine import AdmissionRejected, Engine, Request, ServeConfig
from tony_tpu.serve.frontend import AutoscalePolicy, FrontendRejected, GangFrontend
from tony_tpu.serve.gang import DecodeHostService, GangSettings, build_gang_engine


@pytest.fixture(scope="module")
def tiny():
    import jax

    from tony_tpu.models import llama

    cfg = llama.LlamaConfig.tiny()
    return cfg, llama.init_params(jax.random.key(0), cfg)


def _prompt(n, seed=0):
    return np.random.default_rng(seed).integers(1, 200, n).astype(np.int32)


# --- engine: bounded admission (the frontend's backpressure seam) ------------


def test_engine_bounded_admission(tiny):
    cfg, params = tiny
    eng = Engine(params, cfg, ServeConfig(slots=1, max_len=32, max_queue=2))
    for _ in range(2):
        eng.submit(Request(prompt=_prompt(3), max_new_tokens=2))
    assert eng.queue_depth == 2
    with pytest.raises(AdmissionRejected, match="max_queue 2"):
        eng.submit(Request(prompt=_prompt(3), max_new_tokens=2))
    assert eng.rejected_total == 1
    # the registry counter is the portal-visible twin of the exception
    snap = {e["name"]: e for e in eng.registry.snapshot()}
    assert snap["tony_serve_rejected_total"]["value"] == 1
    # queue drains -> admission reopens
    eng.run()
    eng.submit(Request(prompt=_prompt(3), max_new_tokens=2))
    assert eng.rejected_total == 1
    eng.run()


def test_engine_unbounded_by_default(tiny):
    cfg, params = tiny
    eng = Engine(params, cfg, ServeConfig(slots=1, max_len=32))
    for _ in range(8):
        eng.submit(Request(prompt=_prompt(3), max_new_tokens=1))
    assert eng.queue_depth == 8 and eng.rejected_total == 0
    eng.run()


# --- deterministic re-prefill (satellite: the no-request-lost foundation) ----


def test_deterministic_reprefill_on_fresh_engine(tiny):
    """A request interrupted mid-decode and replayed on a FRESH engine
    with the same rng seed reproduces identical tokens — what makes the
    frontend's re-queue + re-prefill draw-for-draw equal to the stream
    the dead host was producing."""
    cfg, params = tiny
    req = dict(prompt=_prompt(5, seed=3), max_new_tokens=12,
               temperature=0.9, top_k=11, rng=1234)
    # uninterrupted reference on a busy engine
    ref_eng = Engine(params, cfg, ServeConfig(slots=2, max_len=32, kv_block=8))
    ref_eng.submit(Request(prompt=_prompt(4, seed=9), max_new_tokens=6))
    rid = ref_eng.submit(Request(**req))
    ref = ref_eng.run()[rid].tokens
    # "killed" engine: step a few times, then abandon it mid-request
    dead = Engine(params, cfg, ServeConfig(slots=1, max_len=32))
    drid = dead.submit(Request(**req))
    for _ in range(4):
        dead.step()
    partial = list(dead.completion_of(drid).tokens)
    assert 0 < len(partial) < 12 and not dead.completion_of(drid).finish_reason
    # survivor: fresh engine, same seed -> identical stream, prefix included
    surv = Engine(params, cfg, ServeConfig(slots=2, max_len=32))
    srid = surv.submit(Request(**req))
    replay = surv.run()[srid].tokens
    assert replay == ref
    assert replay[: len(partial)] == partial


# --- utils/net: the bind TOCTOU fix ------------------------------------------


def test_bind_with_retry_rides_out_a_stolen_port():
    from tony_tpu.utils.net import bind_with_retry, find_free_port

    port = find_free_port()
    thief = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    thief.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    thief.bind(("127.0.0.1", port))  # the TOCTOU: someone took our pick

    bound_socket = []

    def release_later():
        time.sleep(0.4)
        thief.close()

    threading.Thread(target=release_later, daemon=True).start()

    def bind(p):
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            s.bind(("127.0.0.1", p))
        except OSError:
            s.close()
            raise
        bound_socket.append(s)
        return s.getsockname()[1]

    assert bind_with_retry(bind, port, attempts=8, retry_delay_s=0.2) == port
    bound_socket[-1].close()


def test_bind_with_retry_bounded_failure():
    from tony_tpu.utils.net import bind_with_retry, find_free_port

    port = find_free_port()
    holder = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    holder.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    holder.bind(("127.0.0.1", port))
    attempts = []

    def bind(p):
        attempts.append(p)
        raise OSError("in use")

    with pytest.raises(OSError, match="after 3 attempt"):
        bind_with_retry(bind, port, attempts=3, retry_delay_s=0.01)
    assert len(attempts) == 3
    holder.close()


# --- lease store: autoscale hooks --------------------------------------------


def test_lease_grow_and_shrink_gang(tmp_path):
    from tony_tpu.cluster.backend import Resource
    from tony_tpu.cluster.lease import GangAsk, LeaseStore

    store = LeaseStore(str(tmp_path / "rm"))
    store.register_hosts({"h1": Resource(4096, 8, 8), "h2": Resource(4096, 8, 8)})
    store.reserve_gang(
        "serve-app", [GangAsk(Resource(1024, 2, 4))], gang_id="containers",
        timeout_s=0,
    )
    # grow: non-blocking grant while capacity exists
    got1 = store.grow_gang("serve-app", "autoscale", GangAsk(Resource(2048, 4, 4)))
    got2 = store.grow_gang("serve-app", "autoscale", GangAsk(Resource(2048, 4, 4)))
    assert got1 and got2
    # cluster full for this ask now -> None, never a queue
    assert store.grow_gang(
        "serve-app", "autoscale", GangAsk(Resource(4096, 8, 8))
    ) is None
    # shrink hands capacity back, LIFO
    assert store.shrink_gang("serve-app", "autoscale") == got2
    assert store.shrink_gang("serve-app", "autoscale") == got1
    assert store.shrink_gang("serve-app", "autoscale") is None  # gang emptied
    summary = store.summary()
    assert len(summary["apps"]["serve-app"]["leases"]) == 1  # original gang intact
    # a foreign live owner's gang is refused
    other = LeaseStore(str(tmp_path / "rm"), owner_host="elsewhere")
    assert other.grow_gang(
        "serve-app", "autoscale", GangAsk(Resource(64, 1, 0))
    ) is None
    store.release_app("serve-app")


def test_autoscale_policy_sustained_windows():
    pol = AutoscalePolicy(high=8, low=1, window_s=10.0)
    t = 1000.0
    assert pol.observe(9, t) is None            # above, window starts
    assert pol.observe(12, t + 5) is None       # still above, not sustained
    assert pol.observe(10, t + 11) == "grow"    # sustained a full window
    assert pol.observe(10, t + 12) is None      # window reset after decision
    assert pol.observe(3, t + 13) is None       # mid-band clears both windows
    assert pol.observe(0, t + 14) is None
    assert pol.observe(1, t + 25) == "shrink"
    # disabled policy never decides
    assert AutoscalePolicy(0, 0, 1.0).observe(10**6, t) is None


# --- chaos: condition-triggered faults ---------------------------------------


def test_chaos_on_file_trigger(tmp_path):
    from tony_tpu.chaos import chaos_hook, install_from_config, uninstall
    from tony_tpu.chaos.faults import parse_faults

    trigger = tmp_path / "go"
    specs = parse_faults(json.dumps(
        [{"type": "drop_heartbeats", "on_file": str(trigger), "from_count": 1}]
    ))
    assert specs[0].on_file == str(trigger)
    assert "on_file" in specs[0].describe()
    cfg = TonyConfig({
        "chaos.enabled": True,
        "chaos.faults": json.dumps(
            [{"type": "drop_heartbeats", "on_file": str(trigger)}]
        ),
    })
    try:
        assert install_from_config(cfg, role="executor") is True
        assert chaos_hook("executor.beat", task="w:0") is None  # file absent
        trigger.write_text("")
        assert chaos_hook("executor.beat", task="w:0") is not None
    finally:
        uninstall()


# --- serve invariants over fabricated ledgers --------------------------------


def _app_with_ledger(tmp_path, name, ledger):
    app = tmp_path / name
    (app / "serve").mkdir(parents=True)
    (app / "events").mkdir()
    (app / "status.json").write_text(
        json.dumps({"state": "SUCCEEDED", "exit_code": 0, "tasks": []})
    )
    (app / "events" / f"{name}.jhist.jsonl").write_text(
        json.dumps({"type": "APPLICATION_FINISHED", "ts": 0, "state": "SUCCEEDED"})
        + "\n"
    )
    (app / "serve" / "requests_frontend.json").write_text(json.dumps(ledger))
    return str(app)


def test_serve_invariants_flag_losses_and_pass_clean(tmp_path):
    clean = _app_with_ledger(tmp_path, "clean-app", {
        "proc": "frontend", "ttft_budget_s": 5.0, "rejected": 1, "pending": [],
        "requests": [
            {"rid": "r1", "tokens": 8, "finish_reason": "length",
             "ttft_s": 0.2, "replays": 1, "replay_consistent": True},
            {"rid": "r2", "tokens": 3, "finish_reason": "eos",
             "ttft_s": 0.1, "replays": 0, "replay_consistent": True},
            # explicit rejection is backpressure, not a loss
            {"rid": "r3", "tokens": 0, "finish_reason": "rejected",
             "ttft_s": 0.0, "replays": 0, "replay_consistent": True},
        ],
    })
    assert check_invariants([clean]).ok

    bad = _app_with_ledger(tmp_path, "lossy-app", {
        "proc": "frontend", "ttft_budget_s": 1.0, "pending": ["r9"],
        "requests": [
            {"rid": "r1", "tokens": 0, "finish_reason": "error",
             "message": "replay budget exhausted", "ttft_s": 0.0,
             "replays": 3, "replay_consistent": True},
            {"rid": "r2", "tokens": 8, "finish_reason": "length",
             "ttft_s": 0.2, "replays": 1, "replay_consistent": False},
            {"rid": "r3", "tokens": 8, "finish_reason": "length",
             "ttft_s": 4.0, "replays": 0, "replay_consistent": True},
        ],
    })
    report = check_invariants([bad])
    kinds = [(v.invariant, v.detail) for v in report.violations]
    assert sum(1 for k, _ in kinds if k == "serve-no-request-lost") == 3
    assert any("never completed" in d for _, d in kinds)
    assert any("NON-deterministically" in d for _, d in kinds)
    assert any(k == "serve-ttft-bounded" for k, _ in kinds)


def test_portal_serve_rollup(tmp_path):
    from tony_tpu.obs.portal import PortalData

    _app_with_ledger(tmp_path, "served-app", {
        "proc": "frontend", "rejected": 2, "pending": [],
        "requests": [
            {"rid": "r1", "tokens": 8, "finish_reason": "length",
             "ttft_s": 0.7, "replays": 1, "replay_consistent": True},
            {"rid": "r2", "tokens": 0, "finish_reason": "error",
             "ttft_s": 0.0, "replays": 3, "replay_consistent": True},
            # explicit backpressure: counts as rejected, NOT an error —
            # same semantics as the serve-no-request-lost invariant
            {"rid": "r3", "tokens": 0, "finish_reason": "rejected",
             "ttft_s": 0.0, "replays": 0, "replay_consistent": True},
        ],
    })
    data = PortalData(str(tmp_path))
    s = data.serve_summary("served-app")
    assert s["requests"] == 3 and s["finished"] == 1 and s["errors"] == 1
    assert s["replays"] == 4 and s["rejected"] == 3
    assert s["ttft_max_s"] == 0.7
    fleet = data.serve_summaries()
    assert list(fleet) == ["served-app"]
    assert data.serve_summary("no-such-app!") is None


# --- settings / runtime export -----------------------------------------------


def test_gang_settings_roundtrip_and_runtime_env():
    from tony_tpu.runtime import make_runtime
    from tony_tpu.runtime.base import TaskIdentity

    cfg = TonyConfig({
        "serve.gang.hosts": 3, "serve.gang.model": "tiny",
        "serve.gang.slots": 2, "serve.gang.max_queue": 5,
        "serve.gang.ttft_budget_s": 2.5,
        "job.decode.instances": 3,
    })
    settings = GangSettings.from_config(cfg)
    assert settings.hosts == 3 and settings.max_queue == 5
    assert GangSettings.from_json(settings.to_json()) == settings

    rt = make_runtime("serve")
    rt.validate(cfg)
    identity = TaskIdentity(
        job_name="decode", index=1,
        cluster_spec={"decode": ["h0:7001", "h1:7002", "h2:7003"]},
        coordinator_address="h0:7001", process_id=1, num_processes=3,
    )
    env = rt.build_env(identity, cfg)
    assert env["TONY_SERVE_PORT"] == "7002"
    assert GangSettings.from_json(env["TONY_SERVE_GANG"]) == settings
    # validate refuses a serve job with no gang task type configured
    with pytest.raises(ValueError, match=r"\[job.decode\]"):
        rt.validate(TonyConfig({"serve.gang.hosts": 2}))


# --- frontend against in-process hosts ---------------------------------------


def _start_host(settings, i):
    from tony_tpu.rpc import serve_rpc

    svc = DecodeHostService(lambda: build_gang_engine(settings), f"decode:{i}")
    server, port = serve_rpc(svc, host="127.0.0.1", port=0)
    svc.start()
    return svc, server, port


def test_frontend_routes_fails_over_and_drains():
    """In-process gang of 2: batch completes across both hosts; a hard
    host kill mid-stream re-queues + re-prefills on the survivor with the
    delivered prefix verified; rolling drain recycles the survivor."""
    settings = GangSettings(
        model="tiny", slots=2, max_len=128, max_queue=8, max_replays=3,
    )
    h0 = _start_host(settings, 0)
    h1 = _start_host(settings, 1)
    fe = GangFrontend("", settings)
    fe.add_host("decode:0", f"127.0.0.1:{h0[2]}")
    fe.add_host("decode:1", f"127.0.0.1:{h1[2]}")
    try:
        done = fe.run([_prompt(3), _prompt(4), _prompt(5)], max_new_tokens=8)
        assert len(done) == 3
        assert all(c.finish_reason == "length" for c in done.values())
        used = {h for c in done.values() for h in c.hosts}
        assert used == {"decode:0", "decode:1"}  # least-loaded spreads

        # kill decode:0 mid-stream
        rids = [fe.submit(_prompt(4, seed=i), 60) for i in range(4)]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            with fe._lock:
                flights = list(fe._flights.values())
            if any(
                f.result.tokens and f.result.hosts[-1] == "decode:0"
                for f in flights
            ):
                break
            time.sleep(0.05)
        else:
            pytest.fail("no stream reached decode:0 in time")
        h0[1].stop(None)  # hard server death -> RpcError mid-stream
        res = {rid: fe.result(rid, timeout_s=120) for rid in rids}
        assert all(
            c.finish_reason == "length" and len(c.tokens) == 60
            and c.replay_consistent
            for c in res.values()
        ), res
        assert sum(c.replays for c in res.values()) >= 1

        # deterministic validation failures do not burn replays
        bad = fe.result(fe.submit(_prompt(3), 500), timeout_s=30)
        assert bad.finish_reason == "rejected" and bad.replays == 0

        # rolling restart: survivor drains + recycles while registered
        restarted = fe.rolling_restart(recycle=True, timeout_s=10.0)
        assert restarted == ["decode:1"]
        after = fe.run([_prompt(6)], max_new_tokens=4)
        assert all(c.finish_reason == "length" for c in after.values())

        ledger = fe.close()
        assert not ledger["pending"]
        entries = {e["rid"]: e for e in ledger["requests"]}
        assert all(
            e["replay_consistent"] for e in entries.values()
        )
    finally:
        fe._closed.set()
        h0[0].shutdown()
        h1[0].shutdown()
        h1[1].stop(0)


def test_frontend_bounded_admission():
    settings = GangSettings(frontend_max_inflight=2)
    fe = GangFrontend("", settings)
    try:
        fe.submit(_prompt(3), 4)
        fe.submit(_prompt(3), 4)
        with pytest.raises(FrontendRejected, match="max_inflight 2"):
            fe.submit(_prompt(3), 4)
        assert fe._c_rejected.value == 1
    finally:
        fe.close(wait_s=0.0)


def test_frontend_autoscale_tick_calls_store_hooks(tmp_path):
    from tony_tpu.cluster.backend import Resource
    from tony_tpu.cluster.lease import GangAsk, LeaseStore

    store = LeaseStore(str(tmp_path / "rm"))
    store.register_hosts({"h1": Resource(8192, 8, 8)})
    store.reserve_gang(
        "serve-auto", [GangAsk(Resource(1024, 1, 0))], timeout_s=0
    )
    settings = GangSettings(
        autoscale_queue_high=4, autoscale_queue_low=0, autoscale_window_s=1.0,
    )
    fe = GangFrontend(
        "", settings, lease_store=store, app_id="serve-auto",
        grow_ask=GangAsk(Resource(2048, 2, 4)),  # the real container shape
    )
    try:
        t = 100.0
        assert fe.autoscale_tick(10, t) is None
        assert fe.autoscale_tick(10, t + 1.5) == "grow"
        assert fe.autoscale_tick(0, t + 2.0) is None
        assert fe.autoscale_tick(0, t + 3.6) == "shrink"
        actions = [a for a, _ in fe.autoscale_actions]
        assert actions == ["grow", "shrink"]
        # the grow leased the REAL container shape and the shrink returned it
        grow_detail = fe.autoscale_actions[0][1]
        assert "leased h1" in grow_detail
        leases = store.summary()["apps"]["serve-auto"]["leases"]
        assert len(leases) == 1
        # without a grow_ask the decision is recorded but nothing is leased
        fe2 = GangFrontend(
            "", settings, lease_store=store, app_id="serve-auto",
        )
        try:
            t2 = 200.0
            fe2.autoscale_tick(10, t2)
            assert fe2.autoscale_tick(10, t2 + 1.5) == "grow"
            assert "no grow_ask" in fe2.autoscale_actions[0][1]
            assert len(store.summary()["apps"]["serve-auto"]["leases"]) == 1
        finally:
            fe2.close(wait_s=0.0)
    finally:
        fe.close(wait_s=0.0)
        store.release_app("serve-auto")


# --- THE e2e: chaos kill_container on a decode host mid-stream ---------------


@pytest.mark.slow  # ~48s: full client->AM->2-host stack; the pooled
# prefill-kill e2e below keeps the kill/reprefill path under tier-1
def test_gang_serve_e2e_kill_container_midstream(tmp_path):
    """Acceptance: a REAL client -> AM -> 2-decode-host serve job; chaos
    SIGKILLs decode:0's container the heartbeat after the test observes a
    stream mid-flight on it (the on_file trigger). Every in-flight request
    completes on the survivor, the serve-no-request-lost invariant passes
    over the frontend's ledger, and the merged `tony trace` carries the
    serve.reprefill span parented on the original request span."""
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.cli.main import main as cli_main
    from tony_tpu.obs import trace
    from tony_tpu.rpc import ApplicationRpcClient

    trigger = tmp_path / "kill-now"
    cfg = TonyConfig.load(overrides={
        "task.heartbeat_interval_ms": 200,
        "task.max_missed_heartbeats": 20,
        "application.timeout_s": 300,
        "application.stage_dir": str(tmp_path),
        "application.name": "serve-gang-kill",
        "application.framework": "serve",
        "serve.gang.hosts": 2,
        "serve.gang.model": "tiny",
        "serve.gang.slots": 2,
        "serve.gang.max_len": 256,
        "serve.gang.max_queue": 8,
        "serve.gang.ttft_budget_s": 120,
        # speculation stays on through the kill: accepted multi-token
        # steps must not break replay determinism or the ledger
        "serve.spec.enabled": True,
        "job.decode.instances": 2,
        "job.decode.command": f"{sys.executable} -m tony_tpu.serve.gang",
        "job.decode.env": ["JAX_PLATFORMS=cpu"],
        "chaos.enabled": True,
        "chaos.faults": json.dumps([{
            "type": "kill_container", "task": "decode:0",
            "on_file": str(trigger),
        }]),
        "trace.sample_steps": 1,
    })
    client = TonyClient(cfg)
    client.stage()
    client.launch_am()
    app_dir = client.app_dir
    fe = None
    try:
        am_addr = client.am_address(timeout_s=60.0)
        trace.install_from_config(cfg, app_dir, client.app_id, proc="frontend")
        fe = GangFrontend(
            am_addr, GangSettings.from_config(cfg), app_dir=app_dir,
        )
        fe.wait_ready(2, timeout_s=150.0)
        rids = [fe.submit(_prompt(4, seed=i), 160) for i in range(4)]
        # arm the kill only once a stream is provably mid-flight on decode:0
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            with fe._lock:
                flights = list(fe._flights.values())
            live0 = [
                f for f in flights
                if f.result.tokens and f.result.hosts
                and f.result.hosts[-1] == "decode:0" and not f.done.is_set()
            ]
            if live0:
                break
            time.sleep(0.05)
        else:
            pytest.fail("no stream reached decode:0 before the kill window")
        trigger.write_text("")  # next decode:0 heartbeat (<=200ms): SIGKILL
        res = {rid: fe.result(rid, timeout_s=180.0) for rid in rids}
        for rid, c in res.items():
            assert c.finish_reason == "length" and len(c.tokens) == 160, (rid, c)
            assert c.replay_consistent, (rid, c)
        replayed = [c for c in res.values() if c.replays]
        assert replayed, "the kill interrupted nothing? (fault did not land)"
        assert any(
            c.hosts[0] == "decode:0" and c.hosts[-1] != "decode:0"
            for c in replayed
        )
        fe.close()
        fe = None
    finally:
        if fe is not None:
            fe.close(wait_s=0.0)
        try:
            with ApplicationRpcClient(
                client.am_address(timeout_s=5.0), timeout_s=5.0
            ) as c:
                c.stop_application("serve e2e done")
        except Exception:
            pass
        code = client.monitor(quiet=True)
        trace.uninstall()  # flush the frontend journal before reading it
    assert code == 143  # deliberate stop -> KILLED

    status = json.load(open(os.path.join(app_dir, "status.json")))
    assert status["state"] == "KILLED"
    # decode:0 went around (failed_only restart of the killed host)
    attempts = {t["task"]: t["attempts"] for t in status["tasks"]}
    assert attempts["decode:0"] >= 2 and attempts["decode:1"] == 1

    # the serving contracts hold post-mortem
    report = check_invariants([app_dir])
    assert report.ok, report.to_json()
    ledger = json.load(
        open(os.path.join(app_dir, "serve", "requests_frontend.json"))
    )
    assert len(ledger["requests"]) == 4 and not ledger["pending"]
    assert any(e["replays"] for e in ledger["requests"])

    # the re-prefill span parents on the original request span, and the
    # merged `tony trace` renders both
    recs = []
    with open(os.path.join(app_dir, "trace", "frontend.jsonl")) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    reqs = {
        r["sid"]: r for r in recs
        if r.get("ph") == "X" and r["name"] == "serve.request"
    }
    refills = [
        r for r in recs if r.get("ph") == "X" and r["name"] == "serve.reprefill"
    ]
    assert refills, "no serve.reprefill span journaled"
    for r in refills:
        assert r["psid"] in reqs
        assert reqs[r["psid"]]["args"]["rid"] == r["args"]["rid"]
    assert cli_main(["trace", app_dir]) == 0
    merged = json.load(open(os.path.join(app_dir, "trace.json")))
    names = {e.get("name") for e in merged["traceEvents"]}
    assert {"serve.request", "serve.reprefill", "chaos.kill_container"} <= names


# --- chunked prefill ----------------------------------------------------------


def test_chunk_tokens_must_be_block_aligned(tiny):
    cfg, params = tiny
    with pytest.raises(ValueError, match="multiple of kv_block"):
        Engine(params, cfg, ServeConfig(
            slots=1, max_len=64, kv_block=8, chunk_tokens=12,
        ))


@pytest.mark.slow  # four features live at once means paying every engine
# signature twice; the cheap chunking/handoff tests above keep tier-1 cover
def test_chunked_prefill_parity_with_everything_live(tiny):
    """The chunked-prefill acceptance gate: long unshared tails prefill in
    block-aligned chunks interleaved with decode steps, with prefix
    sharing, speculation, quantized KV AND int8 weights all live — and the
    tokens stay draw-for-draw what generate()'s identical quantized step
    produces. Chunking reshapes the schedule, never the stream."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.models.generate import generate

    cfg, params = tiny
    sv = dict(quant_kv="int8", quant_weights=True, prefix=True,
              spec=True, spec_max_draft=3)
    B, P, m = 3, 40, 6
    rng = np.random.default_rng(11)
    shared = rng.integers(1, cfg.vocab_size, 16)
    prompts = np.stack([
        np.concatenate([shared, rng.integers(1, cfg.vocab_size, P - 16)])
        for _ in range(B)
    ]).astype(np.int32)
    key = jax.random.key(3)
    keys = jax.random.split(key, B)
    eng = Engine(params, cfg, ServeConfig(
        slots=B, max_len=P + m, kv_block=8, chunk_tokens=16, **sv,
    ))
    rids = [
        eng.submit(Request(prompt=prompts[i], max_new_tokens=m, rng=keys[i]))
        for i in range(B)
    ]
    eng.step()
    assert eng._chunking, "40-token prompts over chunk_tokens=16 must chunk"
    assert eng.stats_snapshot()["chunking_slots"] >= 1
    got = eng.run()
    solo = generate(
        params, jnp.asarray(prompts), cfg, max_new_tokens=m, rng=key,
        serve=sv,
    )
    for i, rid in enumerate(rids):
        assert got[rid].tokens == list(np.asarray(solo[i, P:])), i


# --- blockwise KV handoff: serialization + adoption ---------------------------


def _handoff_cfg(**kw):
    base = dict(slots=2, max_len=64, kv_block=8, prefix=True)
    base.update(kw)
    return ServeConfig(**base)


def test_handoff_payload_roundtrip_bit_exact(tiny):
    from tony_tpu.serve.cache import pack_payload, unpack_payload

    cfg, params = tiny
    for quant in ("", "int8"):
        eng = Engine(params, cfg, _handoff_cfg(quant_kv=quant))
        p = _prompt(32, seed=21)
        eng.run([Request(prompt=p, max_new_tokens=1)])
        covered, payload = eng.export_prefix_blocks([int(t) for t in p])
        assert len(covered) == payload.n_blocks * 8 == 32
        packed = pack_payload(payload)
        back = unpack_payload(
            packed["k"], packed["v"], packed["shape"], packed["dtype"],
            k_scale=packed.get("k_scale", b""),
            v_scale=packed.get("v_scale", b""),
        )
        assert np.array_equal(np.asarray(payload.k), np.asarray(back.k))
        assert np.array_equal(np.asarray(payload.v), np.asarray(back.v))
        if quant:
            assert np.array_equal(
                np.asarray(payload.k_scale), np.asarray(back.k_scale)
            )
            assert np.array_equal(
                np.asarray(payload.v_scale), np.asarray(back.v_scale)
            )
        else:
            assert back.k_scale is None and back.v_scale is None
    # malformed payloads are refused, never adopted as garbage
    with pytest.raises(ValueError):
        unpack_payload(
            packed["k"][:-3], packed["v"], packed["shape"], packed["dtype"],
            k_scale=packed["k_scale"], v_scale=packed["v_scale"],
        )
    with pytest.raises(ValueError):
        unpack_payload(packed["k"], packed["v"], [1, 2], packed["dtype"])


@pytest.mark.slow  # three engines + a solo generate; the bit-exact
# roundtrip test above carries the wire format in tier-1
def test_export_adopt_refcount_cow_and_scratch(tiny):
    """Adopted blocks enter the pool through the normal refcount rules:
    fresh allocations (never the scratch block), exactly one owning store
    reference each, idempotent re-ship frees everything, and a decode on
    the adopter rides the hit draw-for-draw with solo generate()."""
    import jax.numpy as jnp

    from tony_tpu.models.generate import generate

    cfg, params = tiny
    sv = dict(quant_kv="int8", prefix=True)
    src = Engine(params, cfg, _handoff_cfg(quant_kv="int8"))
    p = _prompt(32, seed=22)
    src.run([Request(prompt=p, max_new_tokens=1)])
    covered, payload = src.export_prefix_blocks([int(t) for t in p])
    dst = Engine(params, cfg, _handoff_cfg(quant_kv="int8"))
    assert dst.adopt_blocks(covered, payload) == (payload.n_blocks, 0)
    # idempotent re-ship: the prefix is already resident, every block frees
    assert dst.adopt_blocks(covered, payload) == (0, payload.n_blocks)
    m = dst._store.match([int(t) for t in p], 32)
    assert len(m.full) == 4 and 0 not in m.full   # scratch never adopted
    assert all(dst._pool._ref[pid] == 1 for pid in m.full)
    rid = dst.submit(Request(prompt=p, max_new_tokens=4))
    out = dst.run()
    solo = generate(
        params, jnp.asarray(p)[None], cfg, max_new_tokens=4, serve=sv,
    )
    assert out[rid].tokens == list(np.asarray(solo[0, 32:]))
    assert dst.stats_snapshot()["prefix_hit_tokens"] >= 24
    # a geometry/dtype mismatch is refused, never adopted as garbage
    plain = Engine(params, cfg, _handoff_cfg(quant_kv=""))
    with pytest.raises(ValueError, match="incompatible"):
        plain.adopt_blocks(covered, payload)


@pytest.mark.slow  # mid-decode handoff needs a live multi-slot engine;
# the pooled kill e2e below exercises the same race under tier-1
def test_handoff_racing_slot_free_never_corrupts(tiny):
    """A handoff landing while a slot is mid-decode (or just freed) can
    only allocate refcount-zero blocks: the live stream's blocks stay
    untouched and its tokens stay draw-for-draw identical."""
    import jax.numpy as jnp

    from tony_tpu.models.generate import generate

    cfg, params = tiny
    src = Engine(params, cfg, _handoff_cfg())
    p = _prompt(32, seed=23)
    src.run([Request(prompt=p, max_new_tokens=1)])
    covered, payload = src.export_prefix_blocks([int(t) for t in p])

    dst = Engine(params, cfg, _handoff_cfg())
    q = _prompt(24, seed=24)
    rid = dst.submit(Request(prompt=q, max_new_tokens=6))
    for _ in range(3):
        dst.step()        # prefill + decode steps: the slot is live
    slot = next(s for s, r in enumerate(dst._slot_rid) if r == rid)
    live = set(dst._table[slot, :dst._slot_blocks[slot]].tolist())
    created, freed = dst.adopt_blocks(covered, payload)
    adopted = set(dst._store.match([int(t) for t in p], 32).full)
    assert created == payload.n_blocks and freed == 0
    assert not (adopted & live), "adoption wrote into a live slot's blocks"
    out = dst.run()
    solo = generate(params, jnp.asarray(q)[None], cfg, max_new_tokens=6)
    assert out[rid].tokens == list(np.asarray(solo[0, 24:]))
    # the finished slot's release returned blocks to the free list; a stale
    # re-ship of the same payload still only touches refcount-zero ids and
    # the pool's books stay balanced (used == store-owned)
    while dst._store.evict_lru(dst._pool.release) is not None:
        pass
    assert dst.adopt_blocks(covered, payload) == (payload.n_blocks, 0)
    owned = dst._store.match([int(t) for t in p], 32).full
    assert all(dst._pool._ref[pid] == 1 for pid in owned)


# --- per-pool autoscale -------------------------------------------------------


def test_autoscale_policy_per_pool_windows():
    """Each pool sustains its OWN window: a hot prefill pool must not
    inherit the decode pool's timer (or vice versa)."""
    pol = AutoscalePolicy(high=4, low=1, window_s=10)
    t = 1000.0
    assert pol.observe(9, t, pool="prefill") is None
    assert pol.observe(0, t + 5, pool="decode") is None
    # prefill has sustained 11s above; decode has sustained only 6s below
    assert pol.observe(9, t + 11, pool="prefill") == "grow"
    assert pol.observe(0, t + 11, pool="decode") is None
    assert pol.observe(0, t + 16, pool="decode") == "shrink"


def test_frontend_autoscale_per_pool_grows_the_right_ask(tmp_path):
    """A dict tick scales each pool independently and a grow leases that
    pool's own container shape — a heterogeneous gang must never grow the
    wrong pool."""
    from tony_tpu.cluster.backend import Resource
    from tony_tpu.cluster.lease import GangAsk, LeaseStore

    store = LeaseStore(str(tmp_path / "rm"))
    store.register_hosts({"h1": Resource(16384, 16, 8)})
    store.reserve_gang(
        "serve-pools", [GangAsk(Resource(1024, 1, 0))], timeout_s=0
    )
    settings = GangSettings(
        autoscale_queue_high=4, autoscale_queue_low=0, autoscale_window_s=1.0,
    )
    fe = GangFrontend(
        "", settings, lease_store=store, app_id="serve-pools",
        grow_asks={
            "decode": GangAsk(Resource(2048, 2, 4)),
            "prefill": GangAsk(Resource(4096, 4, 2)),
        },
    )
    try:
        t = 100.0
        fe.autoscale_tick({"decode": 2, "prefill": 9}, t)
        assert fe.autoscale_tick({"decode": 2, "prefill": 9}, t + 1.5) == "grow"
        actions = fe.autoscale_actions
        assert [a for a, _ in actions] == ["grow"]
        assert "pool=prefill" in actions[0][1] and "leased h1" in actions[0][1]
        leases = store.summary()["apps"]["serve-pools"]["leases"]
        grown = [l for l in leases if l["memory_mb"] != 1024]
        # the lease carries the PREFILL container shape, not the decode one
        assert len(grown) == 1
        assert grown[0]["memory_mb"] == 4096 and grown[0]["tpu_chips"] == 2
        # ...booked under the prefill pool's own gang so a shrink of one
        # pool can never hand back the other's container
        assert store.shrink_gang("serve-pools", "serve-autoscale-prefill")
        assert store.shrink_gang("serve-pools", "serve-autoscale") is None
    finally:
        fe.close(wait_s=0.0)
        store.release_app("serve-pools")


# --- handoff ledger invariant: firing + non-firing fixtures -------------------


def test_handoff_invariant_fires_and_passes(tmp_path):
    ok_req = {"rid": "r1", "tokens": 8, "finish_reason": "length",
              "ttft_s": 0.2, "replays": 0, "replay_consistent": True}
    clean = _app_with_ledger(tmp_path, "handoff-clean", {
        "proc": "frontend", "pending": [], "requests": [ok_req],
        "handoffs": [
            # balanced success, and a failed handoff whose request still
            # completed via re-prefill: both pass
            {"rid": "r1", "prefill_host": "prefill:0",
             "decode_host": "decode:0", "shipped": 4, "adopted": 3,
             "freed": 1, "ok": True, "message": ""},
            {"rid": "r1", "prefill_host": "prefill:0",
             "decode_host": "decode:0", "shipped": 0, "adopted": 0,
             "freed": 0, "ok": False, "message": "prefill host lost"},
        ],
    })
    assert check_invariants([clean]).ok

    bad = _app_with_ledger(tmp_path, "handoff-leaky", {
        "proc": "frontend", "pending": [],
        "requests": [ok_req,
                     {"rid": "r2", "tokens": 0, "finish_reason": "error",
                      "ttft_s": 0.0, "replays": 0,
                      "replay_consistent": True}],
        "handoffs": [
            # 4 shipped but only 3 accounted for on the adopter: a leak
            {"rid": "r1", "shipped": 4, "adopted": 2, "freed": 1,
             "ok": True, "message": ""},
            # failed handoff AND the request never completed: stranded
            {"rid": "r2", "shipped": 1, "adopted": 0, "freed": 0,
             "ok": False, "message": "ship failed"},
        ],
    })
    report = check_invariants([bad])
    leaks = [v for v in report.violations
             if v.invariant == "handoff-no-block-leak"]
    assert len(leaks) == 2
    assert any("leaked" in v.detail for v in leaks)
    assert any("never completed" in v.detail for v in leaks)


# --- pooled frontend: handoff happy path + prefill-host kill mid-handoff ------


def test_pooled_frontend_prefill_kill_mid_handoff(tmp_path):
    """In-process disaggregated gang (1 prefill + 1 decode host). First a
    clean handoff: blocks ship ahead of the Generate and the decode host
    admits on the hit. Then chaos arms an on_file delay at the
    serve.handoff seam (post-export, pre-ship) and the prefill host is
    hard-killed inside that window: the frontend's Prefill RPC dies
    mid-handoff, the record lands ok=False, the request still completes
    via re-prefill on the decode host, and both serve invariants
    (no-request-lost + handoff-no-block-leak) pass over the real ledger."""
    from tony_tpu.chaos import active_injector, install_from_config, uninstall
    from tony_tpu.rpc import serve_rpc

    settings = GangSettings(
        model="tiny", slots=2, max_len=128, max_queue=8,
        prefill_hosts=1, handoff_min_tokens=64,
    )
    svc_p = DecodeHostService(
        lambda: build_gang_engine(settings, pool="prefill"),
        "prefill:0", pool="prefill",
    )
    svc_d = DecodeHostService(
        lambda: build_gang_engine(settings, pool="decode"),
        "decode:0", pool="decode",
    )
    srv_p, port_p = serve_rpc(svc_p, host="127.0.0.1", port=0)
    srv_d, port_d = serve_rpc(svc_d, host="127.0.0.1", port=0)
    svc_p.start()
    svc_d.start()
    fe = GangFrontend("", settings)
    fe.add_host("decode:0", f"127.0.0.1:{port_d}", pool="decode")
    fe.add_host("prefill:0", f"127.0.0.1:{port_p}", pool="prefill")
    trigger = tmp_path / "kill-now"
    try:
        # clean handoff: the decode pool serves, the prefill pool ships
        c1 = fe.result(fe.submit(_prompt(80, seed=31), 4), timeout_s=120)
        assert c1.finish_reason == "length" and c1.hosts == ["decode:0"]
        with fe._lock:
            h1 = dict(fe._handoffs[-1])
        assert h1["ok"] and h1["shipped"] == h1["adopted"] + h1["freed"] > 0
        # the wire-visible pool label (tony top's split view reads this)
        from tony_tpu.rpc.service import ServeRpcClient

        with ServeRpcClient(f"127.0.0.1:{port_p}") as cli:
            assert cli.decode_stats().pool == "prefill"
        with ServeRpcClient(f"127.0.0.1:{port_d}") as cli:
            assert cli.decode_stats().pool == "decode"

        # arm the mid-handoff window and kill the prefill host inside it
        cfg = TonyConfig({
            "chaos.enabled": True,
            "chaos.faults": json.dumps([{
                "type": "delay_point", "point": "serve.handoff",
                "on_file": str(trigger), "delay_ms": 2500,
            }]),
        })
        assert install_from_config(cfg, role="serve") is True
        trigger.write_text("")
        rid = fe.submit(_prompt(80, seed=32), 4)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            inj = active_injector()
            if inj is not None and inj.fired:
                break
            time.sleep(0.02)
        else:
            pytest.fail("the serve.handoff fault never fired")
        srv_p.stop(None)  # hard prefill-host death, Prefill RPC in flight
        c2 = fe.result(rid, timeout_s=120)
        assert c2.finish_reason == "length" and len(c2.tokens) == 4
        assert c2.hosts == ["decode:0"]   # completed via re-prefill there
        with fe._lock:
            h2 = dict(fe._handoffs[-1])
        assert not h2["ok"] and "prefill host lost" in h2["message"]

        ledger = fe.close()
        assert not ledger["pending"] and len(ledger["handoffs"]) == 2
        app = _app_with_ledger(tmp_path, "pooled-app", ledger)
        report = check_invariants([app])
        assert report.ok, report.to_json()
    finally:
        uninstall()
        fe._closed.set()
        svc_p.shutdown()
        svc_d.shutdown()
        srv_p.stop(0)
        srv_d.stop(0)


# --- tony top: pool column + per-pool quantile rollup -------------------------


def test_top_pool_column_and_rollup(tmp_path):
    from tony_tpu.obs.top import build_view, render

    app = tmp_path / "app-pools"
    sdir = app / "series"
    sdir.mkdir(parents=True)
    now = time.time()
    (sdir / "decode_0_user.jsonl").write_text(json.dumps({
        "ts": now - 1, "pool": "decode", "queue_depth": 2, "occupancy": 0.5,
        "ttft_n": 8, "ttft_p50_s": 0.2, "ttft_p99_s": 0.9,
        "tpot_n": 80, "tpot_p50_s": 0.01, "tpot_p99_s": 0.05,
    }) + "\n")
    # AM-rollup row: the numeric push dropped the pool string, so the
    # task TYPE is the membership
    (sdir / "am_rollup.json").write_text(json.dumps({"tasks": {
        "prefill:0": {"last_ts": now - 1, "points": [{
            "ts": now - 1, "queue_depth": 1, "occupancy": 0.25,
            "ttft_n": 4, "ttft_p50_s": 0.6, "ttft_p99_s": 1.4,
        }]},
    }}))
    (app / "status.json").write_text(
        json.dumps({"state": "RUNNING", "exit_code": "", "tasks": []})
    )
    view = build_view(str(app), now=now)
    rows = {r["proc"]: r for r in view["rows"]}
    assert rows["decode_0_user"]["pool"] == "decode"
    assert rows["prefill:0"]["pool"] == "prefill"
    pools = view["pools"]
    assert pools["decode"]["hosts"] == 1 and pools["prefill"]["hosts"] == 1
    assert pools["decode"]["ttft_p99_s"] == 0.9
    assert pools["decode"]["tpot_p50_s"] == 0.01
    assert pools["prefill"]["ttft_p99_s"] == 1.4
    frame = render(view)
    assert "pool decode:" in frame and "pool prefill:" in frame
    assert "tpot p50/p99" in frame

"""Grouped (dropless) MoE dispatch: parity, dropless semantics, ep sharding.

The grouped path (parallel.moe dispatch='grouped' over ops.grouped_mm) is
validated against the gather/einsum capacity reference the same way every
kernel in this repo is: identical values AND gradients on undropped tokens,
explicit divergence exactly where the semantics differ (forced overflow),
and mesh-sharded == replicated.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.parallel.mesh import MeshShape, build_mesh, set_default_mesh
from tony_tpu.parallel.moe import (
    MoEConfig,
    init_moe_params,
    moe_block,
    routing_stats,
)

BASE = MoEConfig(dim=32, ffn_dim=64, n_experts=4, top_k=2, capacity_factor=8.0)


@pytest.fixture(scope="module")
def params():
    return init_moe_params(jax.random.key(0), BASE, dtype=jnp.float32)


@pytest.fixture(scope="module")
def x():
    return jax.random.normal(jax.random.key(1), (2, 24, 32), jnp.float32)


def run(params, x, **kw):
    cfg = dataclasses.replace(BASE, **kw)

    def loss(p, xx):
        y, aux = moe_block(p, xx, cfg)
        return jnp.sum(y * y) + aux

    val, grads = jax.value_and_grad(loss)(params, x)
    y, aux = moe_block(params, x, cfg)
    return val, grads, y, aux


@pytest.mark.parametrize("gmm_impl", ["scan", "pallas"])
def test_grouped_matches_gather_values_and_grads(params, x, gmm_impl):
    """With ample capacity nothing is dropped, so the dropless grouped path
    (both the lax.scan fallback and the interpreted pallas kernel) must
    reproduce the gather dispatch exactly: outputs, aux loss, and every
    parameter gradient."""
    v_g, g_g, y_g, aux_g = run(params, x, dispatch="gather")
    v_r, g_r, y_r, aux_r = run(params, x, dispatch="grouped", gmm_impl=gmm_impl)
    assert abs(float(v_g) - float(v_r)) < 1e-4
    assert abs(float(aux_g) - float(aux_r)) < 1e-6
    np.testing.assert_allclose(np.asarray(y_r), np.asarray(y_g), atol=1e-5)
    for k in g_g:
        np.testing.assert_allclose(
            np.asarray(g_r[k]), np.asarray(g_g[k]), atol=1e-4, err_msg=k
        )


def test_grouped_matches_einsum_reference(params, x):
    """And against the one-hot einsum reference directly (the original
    GShard formulation every dispatch is anchored to)."""
    _, _, y_e, aux_e = run(params, x, dispatch="einsum")
    _, _, y_r, aux_r = run(params, x, dispatch="grouped")
    assert abs(float(aux_e) - float(aux_r)) < 1e-6
    np.testing.assert_allclose(np.asarray(y_r), np.asarray(y_e), atol=1e-5)


def test_group_block_invariance(params, x):
    """The row-tile size is a layout knob, not a semantic one: outputs and
    grads are identical across block sizes (including one forcing many
    partial tiles)."""
    v8, g8, y8, _ = run(params, x, dispatch="grouped", group_block=8)
    v128, g128, y128, _ = run(params, x, dispatch="grouped", group_block=128)
    assert abs(float(v8) - float(v128)) < 1e-5
    np.testing.assert_allclose(np.asarray(y8), np.asarray(y128), atol=1e-6)
    for k in g8:
        np.testing.assert_allclose(
            np.asarray(g8[k]), np.asarray(g128[k]), atol=1e-5, err_msg=k
        )


def test_dropless_vs_capacity_under_forced_overflow(params, x):
    """At a drop-forcing capacity factor the gather path zeroes overflow
    tokens' FFN delta while grouped serves every route: the outputs MUST
    differ, and grouped must equal the ample-capacity result exactly
    (dropless == infinite capacity, by construction)."""
    _, _, y_tight, _ = run(params, x, dispatch="gather", capacity_factor=0.25)
    _, _, y_ample, _ = run(params, x, dispatch="gather", capacity_factor=100.0)
    _, _, y_grouped, _ = run(
        params, x, dispatch="grouped", capacity_factor=0.25
    )
    # sanity: the tight capacity really dropped something
    assert float(jnp.max(jnp.abs(y_tight - y_ample))) > 1e-3
    np.testing.assert_allclose(
        np.asarray(y_grouped), np.asarray(y_ample), atol=1e-5
    )
    # and the training loss sees the difference (the dropped tokens' zero
    # delta is a real modeling change, not a numerics blur)
    lt = float(jnp.sum(y_tight * y_tight))
    lg = float(jnp.sum(y_grouped * y_grouped))
    assert abs(lt - lg) > 1e-4


@pytest.mark.parametrize("gmm_impl", ["scan", "pallas"])
def test_empty_expert_is_well_defined(params, x, gmm_impl):
    """An expert the router never picks still produces finite outputs and a
    defined (zero) weight gradient — the layout guarantees every expert at
    least one (zero-padded) row tile, so no dW block is left unwritten."""
    from tony_tpu.parallel.moe import _moe_grouped

    cfg = dataclasses.replace(BASE, dispatch="grouped", gmm_impl=gmm_impl)
    flat = x.reshape(-1, x.shape[-1])
    # router probabilities with expert 0 pinned to zero mass
    logits = jax.random.normal(jax.random.key(9), (flat.shape[0], 4))
    probs = jax.nn.softmax(logits.at[:, 0].set(-1e9), axis=-1)

    def loss(pp):
        y, aux = _moe_grouped(pp, flat, cfg, probs)
        return jnp.sum(y * y) + aux

    val, grads = jax.value_and_grad(loss)(params)
    assert np.isfinite(float(val))
    for k, g in grads.items():
        assert np.isfinite(np.asarray(g)).all(), k
    # the dead expert's FFN weights get exactly zero gradient
    np.testing.assert_array_equal(np.asarray(grads["w1"][0]), 0.0)
    np.testing.assert_array_equal(np.asarray(grads["w2"][0]), 0.0)


def test_ep_mesh_shard_map_parity(params, x):
    """With a default ep mesh registered, the grouped path shard_maps the
    expert FFN over ep (local experts per shard + psum combine): values and
    grads must match the unsharded single-device path exactly."""
    cfg = dataclasses.replace(BASE, dispatch="grouped")

    def loss(p, xx):
        y, aux = moe_block(p, xx, cfg)
        return jnp.sum(y * y) + aux

    set_default_mesh(None)
    expect_y, expect_aux = moe_block(params, x, cfg)
    expect_g = jax.grad(loss)(params, x)

    mesh = build_mesh(MeshShape(ep=2, fsdp=2))
    set_default_mesh(mesh)
    try:
        got_y, got_aux = jax.jit(lambda p, a: moe_block(p, a, cfg))(params, x)
        got_g = jax.jit(jax.grad(loss))(params, x)
    finally:
        set_default_mesh(None)
    assert abs(float(got_aux) - float(expect_aux)) < 1e-6
    np.testing.assert_allclose(np.asarray(got_y), np.asarray(expect_y), atol=1e-5)
    for k in expect_g:
        np.testing.assert_allclose(
            np.asarray(got_g[k]), np.asarray(expect_g[k]), atol=1e-4, err_msg=k
        )


def test_ep_sharded_params_under_jit(params, x):
    """dispatch='grouped' with expert weights device_put over an ep mesh
    (no default mesh: plain GSPMD auto-sharding) stays exact — the sort/
    scatter dispatch partitions correctly under jit."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.parallel.moe import logical_axes
    from tony_tpu.parallel.sharding import DEFAULT_RULES, tree_shardings

    cfg = dataclasses.replace(BASE, dispatch="grouped")
    expect, _ = moe_block(params, x, cfg)

    mesh = build_mesh(MeshShape(ep=2, fsdp=2, tp=2))
    shardings = tree_shardings(logical_axes(), mesh, DEFAULT_RULES)
    params_s = jax.device_put(params, shardings)
    x_s = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"), None, None)))
    got, _ = jax.jit(lambda p, a: moe_block(p, a, cfg))(params_s, x_s)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-5)


def test_model_level_grouped_llama(x):
    """LlamaConfig(moe_dispatch='grouped') end to end: the tiny MoE model's
    loss and gradients match the gather dispatch at ample capacity."""
    from tony_tpu.models.llama import LlamaConfig, init_params, loss_fn

    def run_model(dispatch):
        cfg = LlamaConfig.tiny_moe(
            moe_dispatch=dispatch, moe_capacity_factor=8.0
        )
        p = init_params(jax.random.key(0), LlamaConfig.tiny_moe())
        toks = jax.random.randint(jax.random.key(1), (4, 33), 0, cfg.vocab_size)
        val, grads = jax.value_and_grad(loss_fn)(p, toks, cfg)
        return val, grads

    v_g, g_g = run_model("gather")
    v_r, g_r = run_model("grouped")
    assert abs(float(v_g) - float(v_r)) < 1e-5
    flat_g = jax.tree.leaves(g_g)
    flat_r = jax.tree.leaves(g_r)
    for a, b in zip(flat_r, flat_g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_capacity_rounds_to_sublane_multiple():
    """capacity() is always a multiple of 8 (fp32 TPU sublane tile) and
    never below the exact ceil it used to return."""
    for cf, k, e, t in [(1.25, 2, 8, 100), (0.25, 2, 4, 32), (1.0, 1, 3, 7)]:
        cfg = MoEConfig(dim=8, ffn_dim=16, n_experts=e, top_k=k,
                        capacity_factor=cf)
        cap = cfg.capacity(t)
        assert cap % 8 == 0
        assert cap >= max(1, int(np.ceil(cf * k * t / e)))


def test_router_math_is_fp32_for_bf16_inputs():
    """Satellite numerics guard: even with bf16 activations AND a bf16
    router, the softmax/aux math runs in fp32 — the block's probabilities
    match an explicit fp32 recomputation from the same (bf16-rounded)
    operands to fp32 precision, not bf16 precision."""
    cfg = dataclasses.replace(BASE, dispatch="grouped")
    p32 = init_moe_params(jax.random.key(3), cfg, dtype=jnp.float32)
    p16 = {k: v.astype(jnp.bfloat16) for k, v in p32.items()}
    x16 = jax.random.normal(
        jax.random.key(4), (2, 16, 32), jnp.float32
    ).astype(jnp.bfloat16)

    _, aux = moe_block(p16, x16, cfg)
    assert aux.dtype == jnp.float32

    # fp32 reference from the SAME bf16-rounded inputs: if the block's
    # internal math were bf16, this would miss by ~1e-2, not 1e-6
    from tony_tpu.parallel.moe import _top_k_select

    flat = x16.reshape(-1, 32).astype(jnp.float32)
    probs = jax.nn.softmax(flat @ p16["router"].astype(jnp.float32), axis=-1)
    _, _, _, aux_ref = _top_k_select(probs, cfg)
    assert abs(float(aux) - float(aux_ref)) < 1e-6


def test_routing_stats_reports_drops():
    cfg = dataclasses.replace(BASE, capacity_factor=0.25)
    xx = jax.random.normal(jax.random.key(7), (512, 32))
    params = init_moe_params(jax.random.key(8), cfg, dtype=jnp.float32)
    probs = jax.nn.softmax(xx @ params["router"], axis=-1)
    stats = routing_stats(probs, cfg)
    assert 0.0 < stats["dropped_frac"] < 1.0
    assert stats["load_imbalance"] >= 1.0
    assert stats["capacity"] % 8 == 0
    # ample capacity drops nothing
    ample = routing_stats(probs, dataclasses.replace(cfg, capacity_factor=8.0))
    assert ample["dropped_frac"] == 0.0


def test_unknown_dispatch_and_impl_raise(params, x):
    with pytest.raises(ValueError, match="dispatch"):
        moe_block(params, x, dataclasses.replace(BASE, dispatch="nope"))
    with pytest.raises(ValueError, match="gmm impl"):
        moe_block(
            params, x,
            dataclasses.replace(BASE, dispatch="grouped", gmm_impl="nope"),
        )


def test_grouped_is_shard_map_safe():
    """The scan-gmm grouped path runs inside a manual shard_map region (the
    property the pp pipeline stages rely on): a batch-sharded moe_block over
    a manual axis matches the unsharded path exactly — routing is per-token,
    so splitting the batch must not change any token's output."""
    from jax.sharding import PartitionSpec as P

    from tony_tpu.ops.compat import shard_map_compat

    cfg = dataclasses.replace(BASE, dispatch="grouped")
    params = init_moe_params(jax.random.key(0), BASE, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(1), (2, 24, 32), jnp.float32)
    expect, _ = moe_block(params, x, cfg)

    mesh = build_mesh(MeshShape(dp=2))

    def local(p, xx):
        return moe_block(p, xx, cfg)[0]

    got = shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(P(), P("dp", None, None)),
        out_specs=P("dp", None, None),
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=1e-5)


# --- nonfinite-input robustness (the numerics-health contract) ----------------


@pytest.mark.parametrize("gmm_impl", ["scan", "pallas"])
def test_poisoned_tokens_propagate_nonfinite_like_reference(params, x, gmm_impl):
    """A NaN/Inf token riding into the grouped dispatch must PROPAGATE into
    exactly that token's output — never be masked by the sorted/padded
    group layout (padding rows are zeroed by construction; a bug that
    zeroed a real poisoned row the same way would launder the NaN) and
    never smear into clean tokens' outputs. The per-token nonfinite mask
    and the clean tokens' values match the dense per-token reference (the
    gather dispatch). The router aux loss sees every token, so it goes
    nonfinite — the signal obs/health.py's sentinel counts."""
    xp = x.at[0, 5].set(jnp.nan).at[1, 11].set(jnp.inf)

    def out(dispatch, **kw):
        cfg = dataclasses.replace(BASE, dispatch=dispatch, **kw)
        return moe_block(params, xp, cfg)

    y_ref, aux_ref = out("gather")
    y_got, aux_got = out("grouped", gmm_impl=gmm_impl)
    ref = np.asarray(y_ref)
    got = np.asarray(y_got)
    # the reference poisons exactly the poisoned tokens' rows
    bad_ref = {tuple(i[:2]) for i in np.argwhere(~np.isfinite(ref))}
    assert bad_ref == {(0, 5), (1, 11)}
    np.testing.assert_array_equal(np.isfinite(got), np.isfinite(ref))
    finite = np.isfinite(ref)
    np.testing.assert_allclose(got[finite], ref[finite], atol=1e-5)
    # the fp32 router statistics propagate the poison into the aux loss
    assert not np.isfinite(float(aux_ref))
    assert not np.isfinite(float(aux_got))


@pytest.mark.parametrize("gmm_impl", ["scan", "pallas"])
def test_poisoned_expert_weights_propagate_to_routed_tokens(params, x, gmm_impl):
    """NaN in ONE expert's FFN weights must reach exactly the tokens routed
    to that expert (value-matched masks vs the gather reference): the
    grouped GEMM's block-aligned tiles touch only their expert's weights,
    so the poison must neither vanish in padding nor leak across group
    boundaries into other experts' tokens."""
    bad_params = {
        **params,
        "w1": params["w1"].at[2].set(jnp.nan),  # poison expert 2 only
    }

    def out(dispatch, **kw):
        cfg = dataclasses.replace(BASE, dispatch=dispatch, **kw)
        y, _ = moe_block(bad_params, x, cfg)
        return np.asarray(y)

    ref = out("gather")
    got = out("grouped", gmm_impl=gmm_impl)
    # some but not all tokens hit expert 2 at top_k=2 over 4 experts
    assert 0 < (~np.isfinite(ref)).any(axis=-1).sum() < x.shape[0] * x.shape[1]
    np.testing.assert_array_equal(np.isfinite(got), np.isfinite(ref))
    finite = np.isfinite(ref)
    np.testing.assert_allclose(got[finite], ref[finite], atol=1e-5)

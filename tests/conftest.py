"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax imports.

This is the survey's MiniCluster lesson applied to JAX (SURVEY.md section 4):
fake the substrate (devices), keep every framework code path real. Multi-chip
sharding logic runs on 8 virtual CPU devices; single-chip TPU correctness is
exercised separately by bench.py on real hardware.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # env presets axon (TPU); tests force CPU
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# /root/.axon_site/sitecustomize.py imports jax at interpreter start, which
# latches JAX_PLATFORMS=axon before this file runs -- override via the API
# (the backend itself is created lazily, so this still wins).
import jax

jax.config.update("jax_platforms", "cpu")


import pytest


@pytest.fixture(autouse=True)
def _reset_default_mesh():
    """The default mesh is process-global (fit()/tests register it explicitly);
    reset between tests so a mesh from one test can't leak into another's
    model hooks (attention_impl='flash'/'ring')."""
    yield
    from tony_tpu.parallel.mesh import set_default_mesh

    set_default_mesh(None)


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """scripts/lint.py-style budget line: tier-1 runs close to its 870s
    timeout, so every run prints the top-10 slowest tests — future PRs see
    where the wall clock goes BEFORE they blow the budget (the cheap fix
    is usually a slow-mark on a redundant engine build, the PR 14/17
    pattern)."""
    durations = []
    for reports in terminalreporter.stats.values():
        for rep in reports:
            if getattr(rep, "when", "") == "call" and hasattr(rep, "duration"):
                durations.append((rep.duration, rep.nodeid))
    if not durations:
        return
    durations.sort(reverse=True)
    total = sum(d for d, _ in durations)
    top = durations[:10]
    terminalreporter.write_sep(
        "-", f"tier-1 wall clock: {total:.1f}s in test calls; top 10"
    )
    for dur, nodeid in top:
        terminalreporter.write_line(f"  {dur:7.2f}s  {nodeid}")
    terminalreporter.write_line(
        f"  ({sum(d for d, _ in top):.1f}s = "
        f"{100.0 * sum(d for d, _ in top) / total:.0f}% of the call total; "
        "budget 870s — slow-mark redundant heavy tests, don't delete them)"
    )

"""Step anatomy (docs/OBS.md "Step anatomy"): fleet-coordinated profiling
(obs/profile.py), the comms ledger (obs/comms.py), and the per-step budget
report (obs/anatomy.py).

The contract under test, bottom-up:

- HLO collective extraction yields op kind / payload bytes / replica
  groups for a program whose collective set is known by construction;
- the ProfileController captures a real jax.profiler device trace over an
  exact step window, and the anatomy budget's four rows (compute /
  exposed-collective / input-wait / host-blocked) sum to the measured
  step time, with at least one collective row carrying bytes AND measured
  device time;
- the AM broadcast path end to end: a real 2-host job, `tony profile`
  issued over the StartProfile RPC, every host captures, and the merged
  report carries both hosts + the cross-host critical path.
"""

import glob
import json
import os
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.obs import anatomy, comms
from tony_tpu.obs import profile as profile_mod
from tony_tpu.ops.compat import shard_map_compat


@pytest.fixture(autouse=True)
def _no_leaked_controller():
    yield
    profile_mod.uninstall()


def _psum_program():
    """A tiny shard_map program whose optimized HLO contains exactly one
    known all-reduce over all 8 (virtual) devices."""
    n = len(jax.devices())
    mesh = Mesh(np.array(jax.devices()).reshape(n), ("dp",))

    def f(x, w):
        return jax.lax.psum(jnp.dot(x, w), "dp")

    sf = jax.jit(shard_map_compat(
        f, mesh=mesh, in_specs=(P("dp"), P(None, None)), out_specs=P(),
    ))
    x = jnp.ones((n * 16, 64), jnp.float32)
    w = jnp.ones((64, 32), jnp.float32)
    return sf.lower(x, w).compile(), x, w, n


# --- comms ledger: HLO extraction ---------------------------------------------


class TestCommsExtraction:
    def test_known_collective_set_from_compiled_hlo(self):
        compiled, _, _, n = _psum_program()
        rows = comms.extract_collectives(compiled)
        ars = [r for r in rows if r["kind"] == "all-reduce"]
        assert len(ars) == 1, rows
        row = ars[0]
        # result is the reduced f32[1? x 32] block per participant; payload
        # bytes are the result type's size — nonzero and 4-byte aligned
        assert row["bytes"] > 0 and row["bytes"] % 4 == 0
        assert row["name"].startswith("all-reduce")
        groups = row["replica_groups"]
        # one group over every device (parsed {{...}} form) or the iota
        # string form — both must name all n participants
        if isinstance(groups, list):
            assert sorted(sum(groups, [])) == list(range(n))
        else:
            assert str(n) in groups

    def test_text_extraction_covers_kinds_and_skips_done(self):
        text = """
  HloModule m
  ENTRY e {
    %p = f32[8,16]{1,0} parameter(0)
    ROOT %all-reduce.1 = f32[8,16]{1,0} all-reduce(%p), channel_id=1, replica_groups={{0,1},{2,3}}, to_apply=%add
    %all-gather-start.2 = f32[32,16]{1,0} all-gather-start(%p), replica_groups=[2,2]<=[4], dimensions={0}
    %all-gather-done.2 = f32[32,16]{1,0} all-gather-done(%all-gather-start.2)
    %reduce-scatter.3 = bf16[4,16]{1,0} reduce-scatter(%p), replica_groups={{0,1}}, dimensions={0}, to_apply=%add
    %collective-permute.4 = f32[8,16]{1,0} collective-permute(%p), source_target_pairs={{0,1},{1,0}}
    %fusion.9 = f32[8,16]{1,0} fusion(%p), kind=kLoop, calls=%fused
  }
  """
        rows = comms.extract_collectives(text)
        kinds = [r["kind"] for r in rows]
        assert kinds == [
            "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
        ]  # -done skipped, fusion not a collective
        by_kind = {r["kind"]: r for r in rows}
        assert by_kind["all-reduce"]["bytes"] == 8 * 16 * 4
        assert by_kind["all-reduce"]["replica_groups"] == [[0, 1], [2, 3]]
        assert by_kind["all-gather"]["bytes"] == 32 * 16 * 4
        assert by_kind["all-gather"]["replica_groups"] == "[2,2]<=[4]"
        assert by_kind["reduce-scatter"]["bytes"] == 4 * 16 * 2  # bf16

    def test_tuple_result_and_scalar_shapes(self):
        assert comms.shape_bytes("f32[]") == 4
        assert comms.shape_bytes("(f32[2,2]{1,0}, u32[4]{0})") == 16 + 16
        assert comms.shape_bytes("weird[3]") == 0  # unknown dtype: no guess

    def test_record_aot_carries_the_collective_rows(self):
        from tony_tpu.obs.compiles import CompileLedger

        compiled, _, _, _ = _psum_program()
        ledger = CompileLedger()
        entry = ledger.record_aot("probe.step", compiled)
        assert any(
            c["kind"] == "all-reduce" and c["bytes"] > 0
            for c in entry.get("collectives", [])
        ), entry
        # and the anatomy flattener finds them back in a snapshot payload
        rows = anatomy.ledger_collectives(ledger.to_dict())
        assert rows and rows[0]["fn"] == "probe.step"


# --- budget attribution rule (pure interval math) -----------------------------


class TestBudgetRule:
    def test_rows_follow_the_attribution_rule_exactly(self):
        manifest = {"step_time_s": [0.010], "input_wait_s": [0.002]}
        trace_data = {
            "found": True,
            "step_windows": [(0.0, 0.010)],
            "compute": [(0.000, 0.004)],
            "collective": [(0.003, 0.006)],
            "collective_events": [
                {"name": "all-reduce.1", "ts": 0.003, "dur_s": 0.003}
            ],
        }
        b = anatomy.step_budget(manifest, trace_data)
        row = b["table"][0]
        assert row["compute_s"] == pytest.approx(0.004)
        # collective 3..6ms, compute covers 3..4ms -> exposed 4..6ms = 2ms
        assert row["exposed_collective_s"] == pytest.approx(0.002)
        assert row["input_wait_s"] == pytest.approx(0.002)
        assert row["host_blocked_s"] == pytest.approx(0.002)
        total = sum(
            row[k] for k in ("compute_s", "exposed_collective_s",
                             "input_wait_s", "host_blocked_s")
        )
        assert total == pytest.approx(row["step_time_s"])
        # overlap: 1ms of the 3ms collective hidden under compute
        assert b["overlap_frac"] == pytest.approx(1 / 3, abs=1e-3)

    def test_pure_comm_steps_flagged_and_excluded_from_overlap(self):
        """A step with collective time but ZERO compute (a standalone
        reduce, a warmup gather) is flagged pure_comm and kept out of the
        aggregate overlap_frac: there was no compute to hide under, so
        counting its 100%-exposed collective would read as an overlap
        collapse that no scheduling change can fix."""
        manifest = {
            "step_time_s": [0.010, 0.006], "input_wait_s": [0.002, 0.0],
        }
        trace_data = {
            "found": True,
            "step_windows": [(0.0, 0.010), (0.010, 0.016)],
            "compute": [(0.000, 0.004)],  # none lands in step 2
            "collective": [(0.003, 0.006), (0.011, 0.015)],
            "collective_events": [
                {"name": "all-reduce.1", "ts": 0.003, "dur_s": 0.003},
                {"name": "all-gather.2", "ts": 0.011, "dur_s": 0.004},
            ],
        }
        b = anatomy.step_budget(manifest, trace_data)
        assert "pure_comm" not in b["table"][0]
        assert b["table"][0]["compute_s"] == pytest.approx(0.004)
        assert b["table"][1]["pure_comm"] is True
        assert b["table"][1]["compute_s"] == pytest.approx(0.0)
        assert b["table"][1]["exposed_collective_s"] == pytest.approx(0.004)
        assert b["pure_comm_steps"] == 1
        # step 1 alone: 1ms of its 3ms collective hidden (1/3) — step 2's
        # fully exposed 4ms would have dragged this to 1/7 if counted
        assert b["overlap_frac"] == pytest.approx(1 / 3, abs=1e-3)

    def test_no_device_trace_degrades_to_host_residual(self):
        manifest = {"step_time_s": [0.010, 0.008], "input_wait_s": [0.001, 0.0]}
        b = anatomy.step_budget(manifest, {"found": False})
        assert b["device_trace"] is False
        assert b["table"][0]["host_blocked_s"] == pytest.approx(0.009)
        assert b["table"][1]["host_blocked_s"] == pytest.approx(0.008)
        assert "overlap_frac" not in b

    def test_collective_table_keeps_both_one_sided_rows(self):
        trace_data = {"collective_events": [
            {"name": "all-reduce.1", "ts": 0.0, "dur_s": 0.001},
            {"name": "all-reduce.1", "ts": 0.002, "dur_s": 0.003},
            {"name": "all-gather.7", "ts": 0.0, "dur_s": 0.002},
        ]}
        ledger = [
            {"name": "all-reduce.1", "kind": "all-reduce", "bytes": 4096,
             "replica_groups": [[0, 1]]},
            {"name": "reduce-scatter.9", "kind": "reduce-scatter",
             "bytes": 64, "replica_groups": ""},
        ]
        rows = {r["name"]: r for r in anatomy.collective_table(trace_data, ledger)}
        paired = rows["all-reduce.1"]
        assert paired["bytes"] == 4096 and paired["count"] == 2
        assert paired["mean_us"] == pytest.approx(2000.0)
        assert paired["achieved_gbps"] == pytest.approx(
            4096 * 2 / 0.004 / 1e9, rel=1e-3
        )
        assert "achieved_gbps" not in rows["all-gather.7"]     # no bytes
        assert "total_s" not in rows["reduce-scatter.9"]       # never ran


# --- the capture primitive + controller ---------------------------------------


class TestCapture:
    def test_trace_window_returns_the_artifact_path(self, tmp_path):
        from tony_tpu.obs.profiler import trace_window

        compiled, x, w, _ = _psum_program()
        with trace_window(str(tmp_path / "cap")) as cap:
            jax.block_until_ready(compiled(x, w))
        assert cap.ok and cap.path, "capture did not finalise"
        assert os.path.isdir(cap.path)
        # the run dir is where the artifacts actually are — deterministic,
        # no globbing needed by the caller
        assert glob.glob(os.path.join(cap.path, "*.trace.json*"))
        # disabled window: inert handle, nothing written
        with trace_window(str(tmp_path / "off"), enabled=False) as cap2:
            pass
        assert not cap2.ok and cap2.path == ""

    def test_controller_budget_sums_and_collective_row(self, tmp_path):
        """The acceptance shape on CPU: a psum program captured over an
        exact step window; budget rows sum to measured step time within
        10%, and the all-reduce row carries bytes AND measured time."""
        compiled, x, w, _ = _psum_program()
        ledger_rows = comms.extract_collectives(compiled)
        ctl = profile_mod.ProfileController(
            str(tmp_path / "profile"), "probe", watch=False
        )
        req = ctl.trigger(steps=3)
        jax.block_until_ready(compiled(x, w))  # warm outside the window
        for _ in range(5):  # more boundaries than steps: window self-closes
            ctl.step(fetch_s=0.0005)
            jax.block_until_ready(compiled(x, w))
        ctl.finish()

        manifests = profile_mod.read_manifests(str(tmp_path), req.id)
        assert set(manifests) == {"probe"}
        manifest = manifests["probe"]
        assert manifest["steps"] == 3
        assert len(manifest["step_time_s"]) == 3
        assert manifest["artifact"] and os.path.isdir(manifest["artifact"])

        rep = anatomy.proc_report(manifest, ledger_rows)
        assert rep["device_trace"] is True
        for row in rep["table"]:
            attributed = (row["compute_s"] + row["exposed_collective_s"]
                          + row["input_wait_s"] + row["host_blocked_s"])
            assert attributed == pytest.approx(row["step_time_s"], rel=0.10)
        # at least one collective row has static bytes AND measured time
        assert any(
            r.get("bytes", 0) > 0 and r.get("total_s", 0) > 0
            for r in rep["collectives"]
        ), rep["collectives"]

    def test_broadcast_request_arms_at_install_and_expires(self, tmp_path):
        app_dir = str(tmp_path)
        req = profile_mod.write_request(app_dir, steps=2)
        assert profile_mod.read_request(
            profile_mod.request_path(app_dir)
        ).id == req.id
        # a controller armed AFTER the broadcast picks it up synchronously
        ctl = profile_mod.ProfileController(
            profile_mod.profile_dir(app_dir), "w0",
            request_path=profile_mod.request_path(app_dir),
        )
        try:
            assert ctl._pending is not None and ctl._pending.id == req.id
        finally:
            ctl.close()
        # an expired request can never arm
        stale = profile_mod.write_request(app_dir, steps=2, ttl_s=1.0)
        path = profile_mod.request_path(app_dir)
        blob = json.load(open(path))
        blob["deadline_ts"] = time.time() - 5.0
        blob["id"] = stale.id + "x"
        json.dump(blob, open(path, "w"))
        ctl2 = profile_mod.ProfileController(
            profile_mod.profile_dir(app_dir), "w1",
            request_path=path,
        )
        try:
            assert ctl2._pending is None
        finally:
            ctl2.close()

    def test_duration_window_honours_the_step_cap(self, tmp_path):
        """A `--seconds T` window against a fast step loop must stop at
        obs.profile.max_steps, not record an unbounded device trace."""
        ctl = profile_mod.ProfileController(
            str(tmp_path / "profile"), "probe", watch=False, max_steps=3,
        )
        ctl.trigger(duration_s=600.0)
        for _ in range(10):
            ctl.step()
        assert ctl._req is None  # self-closed at the cap, not at 600s
        m = profile_mod.read_manifests(str(tmp_path))["probe"]
        assert m["steps"] == 3

    def test_maybe_capture_disarmed_and_armed_idle_are_inert(self, tmp_path):
        profile_mod.uninstall()
        assert profile_mod.active_controller() is None
        profile_mod.maybe_capture()           # disarmed: pure no-op
        profile_mod.maybe_capture(fetch_s=0.1)
        profile_mod.finish_capture()
        ctl = profile_mod.install(profile_mod.ProfileController(
            str(tmp_path / "profile"), "idle", watch=False
        ))
        for _ in range(100):
            profile_mod.maybe_capture(fetch_s=0.0)
        assert ctl._req is None               # no window ever opened
        assert not os.path.isdir(str(tmp_path / "profile" / "idle"))

    def test_read_manifests_picks_newest_and_filters(self, tmp_path):
        def _mk(proc, cap_id, ts):
            d = tmp_path / "profile" / proc / cap_id
            d.mkdir(parents=True)
            (d / "manifest.json").write_text(json.dumps({
                "profile_id": cap_id, "proc": proc, "ts": ts,
                "steps": 1, "step_time_s": [0.1], "input_wait_s": [0.0],
                "artifact": "",
            }))
        _mk("w0", "p1_a", 100.0)
        _mk("w1", "p1_a", 101.0)
        _mk("w0", "p2_b", 200.0)
        newest = profile_mod.read_manifests(str(tmp_path))
        assert set(newest) == {"w0"} and newest["w0"]["profile_id"] == "p2_b"
        both = profile_mod.read_manifests(str(tmp_path), "p1_a")
        assert set(both) == {"w0", "w1"}
        assert profile_mod.list_captures(str(tmp_path)) == ["p1_a", "p2_b"]


# --- the merged report + CLI --------------------------------------------------


class TestReport:
    def _capture_app(self, tmp_path, procs=("w0", "w1"), scale=(1, 2)):
        compiled, x, w, _ = _psum_program()
        app_dir = str(tmp_path)
        # ONE broadcast id shared by every proc — the AM path's shape
        req = profile_mod.write_request(app_dir, steps=2)
        for proc, mult in zip(procs, scale):
            ctl = profile_mod.ProfileController(
                profile_mod.profile_dir(app_dir), proc, watch=False,
                request_path=profile_mod.request_path(app_dir),
            )
            ctl.check_request()
            for _ in range(4):
                ctl.step()
                for _ in range(mult):  # w1 does 2x work: the straggler
                    jax.block_until_ready(compiled(x, w))
            ctl.finish()
        return app_dir, req.id

    def test_build_anatomy_merges_procs_and_flags_critical_path(self, tmp_path):
        app_dir, _ = self._capture_app(tmp_path)
        rep = anatomy.build_anatomy(app_dir)
        assert set(rep["procs"]) == {"w0", "w1"}
        cp = rep["critical_path"]
        assert cp["proc"] == "w1"  # 2x work per step dominates every step
        assert cp["dominated_steps"]["w1"] == 2
        assert len(cp["by_step"]) == 2

    def test_cli_profile_report(self, tmp_path, capsys):
        from tony_tpu.cli.main import main as cli_main

        app_dir, cap_id = self._capture_app(tmp_path, procs=("w0",), scale=(1,))
        assert cli_main(["profile", "report", app_dir]) == 0
        out = json.loads(capsys.readouterr().out)
        assert out["profile_id"] == cap_id
        assert "w0" in out["procs"]
        assert out["procs"]["w0"]["steps"] == 2
        # empty dir: explicit no-data exit, never a fabricated report
        empty = tmp_path / "empty"
        empty.mkdir()
        assert cli_main(["profile", "report", str(empty)]) == 2

    def test_goodput_reports_unattributed_residual(self):
        from tony_tpu.obs.trace_tool import goodput

        procs = [{
            "proc": "w", "pid": 1, "trace": "t", "dropped": 0,
            "instants": [], "opens": [], "counters": [],
            "spans": [
                {"name": "train.step", "ts": 0, "dur": 1_000_000,
                 "args": {"every": 1}, "sid": "a", "psid": ""},
                {"name": "train.fit", "ts": 0, "dur": 4_000_000,
                 "args": {}, "sid": "b", "psid": ""},
            ],
        }]
        g = goodput("/nonexistent", procs)
        assert g["window_s"] == pytest.approx(4.0)
        assert g["productive_s"] == pytest.approx(1.0)
        # the 3s no bucket claims are REPORTED, not folded silently into
        # the denominator — anatomy and goodput reconcile through this key
        assert g["unattributed_s"] == pytest.approx(3.0)


# --- end-to-end: the AM broadcast over a real 2-host job ----------------------


@pytest.mark.slow  # ~18s: full client->AM->2-executor process stack;
# the capture path (ProfileController window, manifest, proc_report
# math, comms extraction) stays tier-1 in this file's unit/controller
# tests — only the fleet broadcast fan-out re-pays processes here
# (round 20 offsets)
def test_profile_fleet_capture_end_to_end(tmp_path):
    """Acceptance e2e: a REAL client -> AM -> 2-executor job; `tony
    profile <app> --steps 2` broadcast over the StartProfile RPC while the
    workers boot; BOTH hosts capture the window via the app-dir broadcast
    file; the report merges both with a critical path, each host's budget
    rows sum to its measured step time, and at least one collective row
    carries bytes AND measured device time."""
    from tony_tpu.cli.client import TonyClient
    from tony_tpu.cli.main import main as cli_main
    from tony_tpu.config.config import TonyConfig

    src = tmp_path / "src"
    src.mkdir()
    (src / "train.py").write_text(
        "import logging, os\n"
        "logging.basicConfig(level=logging.INFO)\n"
        "# each worker runs an INDEPENDENT tiny fit: the subject here is\n"
        "# the AM profile broadcast + per-host capture, not the data plane\n"
        "os.environ['TONY_NUM_PROCESSES'] = '1'\n"
        "from tony_tpu.train import fit, FitConfig\n"
        "from tony_tpu.train.data import DataConfig\n"
        "from tony_tpu.models.llama import LlamaConfig\n"
        "from tony_tpu.parallel.mesh import MeshShape\n"
        "out = fit(FitConfig(\n"
        "    model=LlamaConfig.tiny(),\n"
        "    data=DataConfig(global_batch=4, seq_len=32, vocab_size=128),\n"
        "    mesh_shape=MeshShape(fsdp=2),\n"
        "    steps=30, log_every=30, warmup_steps=2))\n"
        "print('FIT DONE', out.get('final_loss'))\n"
    )
    cfg = TonyConfig.load(overrides={
        "task.heartbeat_interval_ms": 200,
        "task.max_missed_heartbeats": 10,
        "application.timeout_s": 240,
        "application.stage_dir": str(tmp_path),
        "application.name": "profile-e2e",
        "application.framework": "jax",
        "job.worker.instances": 2,
        "job.worker.command": f"{sys.executable} train.py",
        # 2 virtual CPU devices per worker -> the fsdp=2 mesh all-gathers,
        # so the capture has real collectives to anatomise
        "job.worker.env": [
            "JAX_PLATFORMS=cpu",
            "XLA_FLAGS=--xla_force_host_platform_device_count=2",
        ],
    })
    client = TonyClient(cfg, src_dir=str(src))
    client.stage()
    client.launch_am()
    app_dir = client.app_dir
    try:
        client.am_address()  # AM is up: the broadcast can land
        # trigger via the CLI (the StartProfile RPC path) without waiting —
        # the request file now predates the workers' arming, which is the
        # deterministic pick-up path
        assert cli_main(["profile", app_dir, "--steps", "2", "--no-wait"]) == 0
        # workers boot, arm, capture; manifests land mid-run
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if len(profile_mod.read_manifests(app_dir)) >= 2:
                break
            time.sleep(1.0)
    finally:
        code = client.monitor(quiet=True)
    if code != 0:
        logs_dir = os.path.join(app_dir, "logs")
        for n in sorted(os.listdir(logs_dir)):
            print(f"===== {n}", open(os.path.join(logs_dir, n),
                                     errors="replace").read()[-2000:])
    assert code == 0

    manifests = profile_mod.read_manifests(app_dir)
    assert len(manifests) == 2, sorted(manifests)
    rep = anatomy.build_anatomy(app_dir)
    assert len(rep["procs"]) == 2
    assert rep["critical_path"]["proc"] in rep["procs"]
    saw_paired_collective = False
    for proc, r in rep["procs"].items():
        assert r["steps"] == 2, (proc, r["steps"])
        assert r["device_trace"] is True, proc
        for row in r["table"]:
            attributed = (row["compute_s"] + row["exposed_collective_s"]
                          + row["input_wait_s"] + row["host_blocked_s"])
            assert attributed == pytest.approx(row["step_time_s"], rel=0.10)
        if any(c.get("bytes", 0) > 0 and c.get("total_s", 0) > 0
               for c in r["collectives"]):
            saw_paired_collective = True
    assert saw_paired_collective, {
        p: r["collectives"][:3] for p, r in rep["procs"].items()
    }
    # the trace roll-up points at the capture and reconciles explicitly
    from tony_tpu.obs.trace_tool import report as trace_report

    summary = trace_report(app_dir)
    assert rep["profile_id"] in summary.get("profile_captures", [])
    assert "unattributed_s" in summary["goodput"]
